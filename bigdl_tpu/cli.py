"""bigdl-tpu command line.

Role-equivalent of the reference's `llm-cli` / `llm-chat` shell dispatch
(cli/llm-cli:25-57 in /root/reference — there it picks a per-ISA C++
binary; here every path is the same XLA program) plus `llm_convert`
(convert_model.py:31).

    python -m bigdl_tpu.cli convert  <hf_dir> -o <out_dir> --qtype sym_int4
    python -m bigdl_tpu.cli generate <model_dir> -p "..." -n 64
    python -m bigdl_tpu.cli serve    <model_dir> --port 8000
    python -m bigdl_tpu.cli bench    <model_dir>
    python -m bigdl_tpu.cli chat     <model_dir>
    python -m bigdl_tpu.cli verify   <ckpt_dir | ckpt.npz>
    python -m bigdl_tpu.cli train-status <ckpt_dir>
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _load(path: str, qtype):
    """qtype=None means: native formats for .gguf, sym_int4 for HF dirs."""
    from bigdl_tpu.api import AutoModelForCausalLM

    if path.endswith(".gguf"):
        return AutoModelForCausalLM.from_gguf(path, qtype=qtype)
    import os

    if os.path.exists(os.path.join(path, "bigdl_tpu_config.json")):
        return AutoModelForCausalLM.load_low_bit(path)
    return AutoModelForCausalLM.from_pretrained(
        path, load_in_low_bit=qtype or "sym_int4"
    )


def _tokenizer(path: str):
    try:
        from transformers import AutoTokenizer

        return AutoTokenizer.from_pretrained(path)
    except Exception:
        return None


def _gen_text(model, tok, ids, max_new_tokens, temperature):
    """Shared generate path for the one-shot and chat commands: greedy
    or sampled, EOS/pad TRIMMED before decode (generate_tokens pads the
    fixed [B, max_new] output after EOS — leaking pads corrupts decoded
    text and, in chat mode, every later turn's history)."""
    eos = tok.eos_token_id if tok else None
    out = model.generate(
        [ids], max_new_tokens=max_new_tokens,
        do_sample=temperature > 0, temperature=max(temperature, 1e-5),
        eos_token_id=eos,
    )
    toks = out[0].tolist()
    if eos is not None and eos in toks:
        # cut at EOS: everything after is pad fill (generate_tokens pads
        # the fixed output window) — stripping pad VALUES instead would
        # eat legitimate id-0 tokens when EOS never fired
        toks = toks[: toks.index(eos) + 1]
    return toks, (tok.decode(toks, skip_special_tokens=True)
                  if tok else str(toks))


def cmd_convert(args):
    # gguf export re-encodes weights into the gguf payload type: HF dirs
    # load at bf16 unless the user asked for a low-bit intermediate (or
    # the file would claim q8_0 precision with sym_int4 accuracy);
    # .gguf inputs keep their native per-tensor formats (qtype=None)
    load_q = args.qtype
    if args.format == "gguf" and not args.model.endswith(".gguf"):
        load_q = args.qtype or "bf16"
    model = _load(args.model, load_q)
    if args.format == "gguf":
        from bigdl_tpu.convert.gguf_export import export_gguf
        from bigdl_tpu.models import get_family

        # loaders merge qkv/gate-up by default; split back for export
        # (layouts loaded via from_gguf/low_bit arrive merged too)
        params = model.params
        fam = get_family(model.config.model_type)
        if hasattr(fam, "unmerge_fused_params"):
            params = fam.unmerge_fused_params(params, model.config)
        out = args.output if args.output.endswith(".gguf") \
            else args.output + ".gguf"
        export_gguf(model.config, params, out,
                    qtype=args.gguf_qtype)
        print(f"exported {args.gguf_qtype} gguf to {out}")
        return
    model.save_low_bit(args.output)
    print(f"saved {args.qtype} model to {args.output}")


def cmd_generate(args):
    model = _load(args.model, args.qtype)
    tok = _tokenizer(args.model)
    if tok is None:
        ids = [int(t) for t in args.prompt.split()]
    else:
        ids = list(tok(args.prompt)["input_ids"])
    t0 = time.time()
    toks, text = _gen_text(model, tok, ids, args.max_new_tokens,
                           args.temperature)
    dt = time.time() - t0
    print(text)
    print(
        f"[{len(toks)} tokens in {dt:.2f}s — {1000 * dt / max(len(toks), 1):.1f} ms/token]",
        file=sys.stderr,
    )


def cmd_chat(args):
    """Interactive chat REPL — the reference's `llm-chat` wrapper
    (cli/llm-cli dispatches to main-<family> binaries; here the same
    jitted decode drives a tokenizer chat template when available).

    Turns run through an incremental ChatSession (delta prefill — the
    cache persists across turns, unlike the reference's full-history
    re-prefill); --streaming-window makes the conversation unbounded
    via attention sinks. Families with custom cache adapters fall back
    to one-shot generation."""
    model = _load(args.model, args.qtype)
    if args.adapter:
        # one-tenant chat: fold the adapter into the loaded params
        # (train/qlora.merge_lora) — the REPL serves a single user, so
        # the multi-tenant epilogue machinery would be pure overhead
        from bigdl_tpu.serving.adapters import load_adapter
        from bigdl_tpu.train.qlora import merge_lora

        lora, meta = load_adapter(args.adapter)
        model.params = merge_lora(model.params, lora)
        print(f"note: merged adapter {args.adapter} "
              f"(rank {meta.get('rank')})", file=sys.stderr)
    tok = _tokenizer(args.model)
    history: list[dict] = []

    def new_session():
        from bigdl_tpu.chat import ChatSession

        streaming = ((args.streaming_sink, args.streaming_window)
                     if args.streaming_window else None)
        return ChatSession(model, max_len=args.max_len, streaming=streaming)

    session = None
    consumed: list[int] = []
    try:
        session = new_session()
    except NotImplementedError as e:
        print(f"note: {e}; using one-shot generation", file=sys.stderr)
    templated = tok is not None and getattr(tok, "chat_template", None)
    if args.system:
        if not templated:
            print("warning: --system needs a tokenizer chat template; "
                  "ignored for this model", file=sys.stderr)
        else:
            history.append({"role": "system", "content": args.system})
    print("bigdl-tpu chat — empty line or /exit quits", file=sys.stderr)
    while True:
        try:
            line = input("you> ")
        except (EOFError, KeyboardInterrupt):
            break
        if not line.strip() or line.strip() == "/exit":
            break
        if templated:
            history.append({"role": "user", "content": line})
            ids = list(tok.apply_chat_template(
                history, add_generation_prompt=True
            ))
        elif tok is not None:
            ids = list(tok(line)["input_ids"])
        else:  # no tokenizer: whitespace token ids (testing)
            ids = [int(t) for t in line.split()]
        if session is not None:
            eos = tok.eos_token_id if tok else None
            if ids[: len(consumed)] == consumed and len(ids) > len(consumed):
                delta = ids[len(consumed):]
            else:
                # the template rewrote earlier tokens (or this is the
                # first turn): reset the session (keeps compiled
                # programs) and replay the full ids
                session.reset()
                consumed, delta = [], ids
            try:
                toks = session.send(
                    delta, args.max_new_tokens, eos,
                    temperature=args.temperature,
                )
            except ValueError as e:  # window/max_len overflow
                print(f"note: {e}; restarting context", file=sys.stderr)
                session.reset()
                consumed = []
                try:
                    toks = session.send(ids, args.max_new_tokens, eos,
                                        temperature=args.temperature)
                except ValueError as e2:
                    # even a fresh context cannot fit this turn — tell
                    # the user and keep the REPL alive
                    print(f"error: {e2}", file=sys.stderr)
                    session.reset()
                    continue
            consumed = ids + toks
            text = (tok.decode(toks, skip_special_tokens=True)
                    if tok else str(toks))
        else:
            _, text = _gen_text(model, tok, ids, args.max_new_tokens,
                                args.temperature)
        print(f"bot> {text}")
        if templated:
            history.append({"role": "assistant", "content": text})


def cmd_serve(args):
    from bigdl_tpu.serving.api_server import ApiServer

    from bigdl_tpu.generate import GenerationConfig

    if args.speculative:
        # the sym_int4 self-draft needs a higher-precision target; fail
        # fast BEFORE the (slow) model load, and default the target to
        # bf16 when no qtype was asked for
        if args.qtype is None:
            print("--speculative: loading target as bf16 (self-draft is "
                  "sym_int4); pass -q to override")
            args.qtype = "bf16"
        else:
            from bigdl_tpu.quant.qtypes import resolve_qtype

            try:
                dense = resolve_qtype(args.qtype).is_dense
            except ValueError:
                dense = False
            if not dense:
                raise SystemExit(
                    f"--speculative needs an unquantized target "
                    f"(-q bf16/fp16); got -q {args.qtype}"
                )
    model = _load(args.model, args.qtype)
    # consumed by TpuModel.to_mesh() whenever the model is later sharded
    # over a tp axis (parallel/qcollectives.py wire format for the
    # row-parallel epilogue all-reduces; "none" keeps GSPMD's exact psum)
    model.default_comm_qtype = args.comm_qtype
    tok = _tokenizer(args.model)
    gen = GenerationConfig(
        eos_token_id=(tok.eos_token_id if tok is not None else None)
    )
    adapters = None
    if args.adapter_dir or args.adapter_budget_mb or args.adapters:
        # any adapter flag enables the registry: --adapter-budget-mb
        # without a dir still serves explicit-path POST /adapters/load,
        # and must not be silently ignored
        from bigdl_tpu.serving.adapters import AdapterRegistry

        adapters = AdapterRegistry(
            dir=args.adapter_dir,
            budget_bytes=(args.adapter_budget_mb * (1 << 20)
                          if args.adapter_budget_mb else None),
        )
        for spec in args.adapters or []:
            name, _, path = spec.partition("=")
            desc = adapters.load(name, path=path or None, pin=True)
            print(f"pinned adapter {desc['name']} (rank {desc['rank']})",
                  file=sys.stderr)
    embedder = None
    if args.embedder:
        from bigdl_tpu.convert.hf import open_checkpoint
        from bigdl_tpu.models import bert as B

        with open(os.path.join(args.embedder, "config.json")) as f:
            bcfg = B.BertConfig.from_hf_config(json.load(f))
        get = open_checkpoint(args.embedder)
        embedder = (bcfg, B.params_from_hf(bcfg, get), _tokenizer(args.embedder))
    server = ApiServer(
        model, tokenizer=tok, host=args.host,
        port=args.port, n_slots=args.slots, max_len=args.max_len, gen=gen,
        paged=args.paged,
        prefill_chunk_tokens=args.prefill_chunk_tokens,
        speculative=args.speculative,
        draft_k=args.draft_k, adaptive_draft=args.adaptive_draft,
        embedder=embedder, truncate_prompts=args.truncate_prompts,
        logprobs_top_k=args.logprobs_top_k,
        tracing=args.trace, trace_capacity=args.trace_capacity,
        request_log=args.request_log, adapters=adapters,
    )
    server.start()
    server.install_signal_handlers()  # SIGTERM -> drain, flush, exit 0
    print(f"bigdl-tpu serving {args.model} on {args.host}:{server.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        # ^C gets the same drain as SIGTERM: in-flight requests finish
        # (bounded by request_timeout_s), journal flushed + compacted
        server.shutdown(graceful=True)


def cmd_fastchat_worker(args):
    from bigdl_tpu.serving.fastchat_worker import FastChatWorker

    model = _load(args.model, args.qtype)
    worker = FastChatWorker(
        model, tokenizer=_tokenizer(args.model),
        controller_addr=args.controller_address,
        worker_addr=args.worker_address,
        model_names=(args.model_names.split(",") if args.model_names
                     else None),
        host=args.host, port=args.port, n_slots=args.slots,
        max_len=args.max_len, paged=args.paged,
    )
    worker.start(register=args.controller_address is not None)
    print(f"fastchat worker {worker.worker_id} serving {args.model} "
          f"at {worker.worker_addr}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        worker.shutdown()


def cmd_fetch_iq_tables(args):
    from bigdl_tpu.quant import iq_quants

    url = args.url or iq_quants.DEFAULT_TABLES_URL
    tables = iq_quants.fetch_tables(url=url)
    print(f"cached {sorted(tables)} -> {iq_quants._cache_path()}")


def cmd_txt2img(args):
    from bigdl_tpu.models.sd import load_diffusers_pipeline
    from bigdl_tpu.utils.png import write_png

    pipe = load_diffusers_pipeline(args.model, qtype=args.qtype)

    def as_prompt(text):
        if text is None:
            return None
        toks = text.split()
        if toks and all(t.isdigit() for t in toks):
            return [int(t) for t in toks]  # raw CLIP ids (no tokenizer)
        return text

    imgs = pipe(as_prompt(args.prompt),
                negative_prompt=as_prompt(args.negative),
                height=args.size, width=args.size, num_steps=args.steps,
                guidance_scale=args.guidance, seed=args.seed)
    write_png(args.output, imgs[0])
    print(f"wrote {args.output} ({args.size}x{args.size}, "
          f"{args.steps} steps, cfg {args.guidance})")


def cmd_verify(args):
    """Offline integrity + numerical validation (docs/durability.md):
    `full` mode — sizes/shapes/crc32/sha256 against the artifact's
    integrity manifest plus NaN/inf and scale-range scans — with a
    per-tensor report. Exit code 1 on ANY finding, so CI and operators
    can gate a deploy on a clean checkpoint. Accepts a save_low_bit
    directory or a train-checkpoint .npz (a rotation directory verifies
    every candidate)."""
    path = args.path
    reports = []
    if os.path.isdir(path):
        if os.path.exists(os.path.join(path, "bigdl_tpu_config.json")):
            from bigdl_tpu.convert.low_bit import verify_low_bit

            reports.append(verify_low_bit(path))
        else:
            from bigdl_tpu.train.checkpoint import (
                list_train_checkpoints, verify_train_checkpoint,
            )

            ckpts = list_train_checkpoints(path)
            if not ckpts:
                raise SystemExit(
                    f"{path}: neither a low-bit checkpoint "
                    "(bigdl_tpu_config.json) nor a train-checkpoint "
                    "rotation directory (ckpt-*.npz)"
                )
            reports += [verify_train_checkpoint(p) for p in ckpts]
    elif path.endswith(".npz"):
        from bigdl_tpu.train.checkpoint import verify_train_checkpoint

        reports.append(verify_train_checkpoint(path))
    else:
        raise SystemExit(
            f"{path}: expected a checkpoint directory or a .npz file"
        )
    ok = True
    for rep in reports:
        print(rep.format())
        ok = ok and rep.ok
    if not ok:
        raise SystemExit(1)
    print("OK")


def cmd_train_status(args):
    """Operator view of a training run's checkpoint dir (pairs with
    `bigdl-tpu verify`, which does the full per-tensor audit): rotation
    inventory with fast integrity verdicts, the last-good (newest
    loadable) step a restart would resume from, and the tail of the
    supervisor's structured event log. Exit 1 when checkpoints exist
    but NONE is loadable — a restart would silently start from step 0."""
    import glob as _glob

    from bigdl_tpu.train.checkpoint import (
        inspect_train_checkpoints_dir, list_train_checkpoints,
    )
    from bigdl_tpu.train.supervisor import EventLog

    d = args.ckpt_dir
    if not os.path.isdir(d):
        raise SystemExit(f"{d}: not a checkpoint directory")
    infos = inspect_train_checkpoints_dir(d)
    if not infos:
        print(f"{d}: no rotated checkpoints (ckpt-*.npz)")
    else:
        print(f"{d}: {len(infos)} rotated checkpoint(s), newest first")
        for info in infos:
            status = "ok" if info["ok"] else f"CORRUPT ({info['detail']})"
            size = info["size"]
            mtime = (time.strftime("%Y-%m-%d %H:%M:%S",
                                   time.localtime(info["mtime"]))
                     if info["mtime"] else "?")
            print(f"  {os.path.basename(info['path'])}  "
                  f"step={info['step']}  {size or '?'}B  {mtime}  {status}")
        good = [i for i in infos if i["ok"]]
        if good:
            print(f"last-good step: {good[0]['step']} "
                  f"({os.path.basename(good[0]['path'])})")
        else:
            print("last-good step: NONE — every candidate is corrupt; "
                  "a restart would begin from scratch")
    legacy = os.path.join(d, "train_state.npz")
    if os.path.exists(legacy):
        print(f"legacy single-file checkpoint present: {legacy}")
    events = sorted(_glob.glob(os.path.join(d, "supervisor_events*.jsonl")))
    for ev_path in events:
        # run provenance: the newest `backward` event says which dx path
        # the step function was traced with (fused Pallas vs XLA remat) —
        # scan deeper than the display tail so an old flip isn't missed
        bwd = [e for e in EventLog.tail(ev_path, n=10000)
               if e.get("kind") == "backward"]
        if bwd:
            print(f"backward path: {bwd[-1].get('path')} "
                  f"(recorded at step {bwd[-1].get('step')})")
        tail = EventLog.tail(ev_path, n=args.events)
        print(f"\n{os.path.basename(ev_path)} (last {len(tail)} events):")
        for e in tail:
            ts = time.strftime("%H:%M:%S", time.localtime(e.get("ts", 0)))
            extra = {k: v for k, v in e.items()
                     if k not in ("ts", "step", "kind")}
            print(f"  {ts}  step {e.get('step'):>8}  {e.get('kind'):<16}"
                  + (f" {extra}" if extra else ""))
    if not events:
        print("no supervisor event log (pre-supervisor run, or the "
              "trainer was driven without TrainSupervisor)")
    if infos and not any(i["ok"] for i in infos):
        raise SystemExit(1)


def cmd_trace(args):
    """Observability toolbox against a live server or a dumped trace
    (docs/observability.md):

        bigdl-tpu trace dump http://127.0.0.1:8000 -o trace.json
        bigdl-tpu trace summarize trace.json
        bigdl-tpu trace profile-start http://127.0.0.1:8000 --logdir /tmp/prof
        bigdl-tpu trace profile-stop  http://127.0.0.1:8000

    `dump` fetches the server's span ring buffer as Chrome trace-event
    JSON (loads directly in Perfetto); `summarize` reduces a trace file
    to a per-phase latency table; `profile-start`/`profile-stop` drive
    the server's guarded jax.profiler window."""
    if args.action == "summarize":
        from bigdl_tpu.obs.tracing import format_summary, summarize_trace

        with open(args.target, encoding="utf-8") as f:
            trace = json.load(f)
        print(format_summary(summarize_trace(trace)))
        return
    import urllib.error
    import urllib.request

    base = args.target.rstrip("/")

    def fetch(req_or_path):
        req = req_or_path if not isinstance(req_or_path, str) \
            else base + req_or_path
        path = req if isinstance(req, str) else req.full_url
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            raise SystemExit(f"{path} -> HTTP {e.code}: {body}")
        except urllib.error.URLError as e:
            raise SystemExit(f"cannot reach {path}: {e.reason}")

    def post(path, payload):
        return json.loads(fetch(urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )))

    if args.action == "dump":
        data = fetch("/debug/trace")
        try:
            n = len(json.loads(data).get("traceEvents", []))
        except json.JSONDecodeError:
            raise SystemExit(
                f"{base}/debug/trace returned non-JSON — is this a "
                "bigdl-tpu server?"
            )
        out = args.output
        from bigdl_tpu.utils.durability import atomic_write

        atomic_write(out, lambda f: f.write(data))
        print(f"wrote {n} trace events to {out} — open in Perfetto "
              "(https://ui.perfetto.dev) or chrome://tracing")
    elif args.action == "profile-start":
        if not args.logdir:
            raise SystemExit("profile-start needs --logdir")
        out = post("/debug/profiler", {"action": "start",
                                       "logdir": args.logdir})
        print(f"profiler window open -> {out['logdir']}")
    elif args.action == "profile-stop":
        out = post("/debug/profiler", {"action": "stop"})
        print(f"profiler window closed after {out.get('seconds')}s; "
              f"inspect {out['logdir']} with TensorBoard/XProf")


def cmd_adapters(args):
    """Multi-tenant LoRA adapter lifecycle (docs/serving.md §7) —
    against a live server, or a local artifact:

        bigdl-tpu adapters list   http://127.0.0.1:8000
        bigdl-tpu adapters load   http://127.0.0.1:8000 my-tenant [--path p] [--pin]
        bigdl-tpu adapters unload http://127.0.0.1:8000 my-tenant
        bigdl-tpu adapters inspect path/to/adapter.npz

    `inspect` verifies the artifact offline (full integrity mode) and
    prints its rank/targets/size; the server actions drive the
    registry's load/unload endpoints."""
    if args.action == "inspect":
        from bigdl_tpu.serving.adapters import load_adapter
        from bigdl_tpu.utils.durability import IntegrityError

        try:
            lora, meta = load_adapter(args.target, verify="full")
        except FileNotFoundError:
            raise SystemExit(f"{args.target}: no such adapter artifact")
        except IntegrityError as e:
            # the whole point of inspect is catching this: report the
            # structured finding and exit 1, like `bigdl-tpu verify`
            raise SystemExit(f"FAILED {e}")
        from bigdl_tpu.serving.adapters import lora_nbytes

        print(json.dumps({
            "path": args.target, "rank": meta.get("rank"),
            "scale": meta.get("scale"), "targets": meta.get("targets"),
            "nbytes": lora_nbytes(lora), "verified": "full",
        }, indent=2))
        return
    import urllib.error
    import urllib.request

    base = args.target.rstrip("/")

    def call(path, payload=None):
        req = (base + path if payload is None else urllib.request.Request(
            base + path, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        ))
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            body = e.read().decode(errors="replace")
            raise SystemExit(f"{base}{path} -> HTTP {e.code}: {body}")
        except urllib.error.URLError as e:
            raise SystemExit(f"cannot reach {base}{path}: {e.reason}")

    if args.action == "list":
        out = call("/adapters")
        print(json.dumps(out, indent=2))
    elif args.action == "load":
        if not args.name:
            raise SystemExit("adapters load needs a NAME")
        payload = {"name": args.name, "pin": args.pin}
        if args.path:
            payload["path"] = args.path
        out = call("/adapters/load", payload)
        a = out["adapter"]
        print(f"loaded {a['name']} (rank {a['rank']}, "
              f"{a['nbytes']}B{', pinned' if a['pinned'] else ''})")
    elif args.action == "unload":
        if not args.name:
            raise SystemExit("adapters unload needs a NAME")
        out = call("/adapters/unload", {"name": args.name})
        print(f"unloaded {out['adapter']['name']}")


def cmd_simserve(args):
    """Simulated-clock serving benchmark (docs/benchmarking.md): drive
    the real engine with a seeded synthetic trace under a virtual clock
    and a roofline cost model — engine-level throughput / TTFT / p99 /
    preemption + shed numbers with ZERO devices.

        bigdl-tpu simserve --trace poisson --seed 0
        bigdl-tpu simserve --trace overload -o report.json
        bigdl-tpu simserve --trace-file banked.jsonl

    Prints exactly one JSON report line (sorted keys: two identical
    invocations are byte-identical). `--save-trace` banks the generated
    arrival trace as replayable crc'd JSONL."""
    import jax

    # zero-device contract: never claim the (serialized) TPU tunnel —
    # jax.config, not env: the session sitecustomize overrides env vars
    jax.config.update("jax_platforms", "cpu")
    from bigdl_tpu.sim.engine_driver import (
        SCENARIOS, SimDriver, default_cost_model, report_json,
    )
    from bigdl_tpu.sim.traces import Trace, named_trace

    if args.trace_file:
        trace = Trace.load(args.trace_file)
        sim = SCENARIOS.get(trace.name) or SCENARIOS["poisson"]
    else:
        trace = named_trace(args.trace, seed=args.seed)
        sim = SCENARIOS[args.trace]
    if args.save_trace:
        trace.save(args.save_trace)
        print(f"saved {len(trace.arrivals)}-arrival trace to "
              f"{args.save_trace}", file=sys.stderr)
    if args.speculative or args.draft_k is not None:
        # flag overrides on top of the named scenario: any mix can run
        # through draft+verify rounds (adapter mixes draft with the
        # base and verify with the adapter applied — engine.py §spec)
        import dataclasses as _dc

        sim = _dc.replace(
            sim, speculative=True,
            draft_k=sim.draft_k if args.draft_k is None else args.draft_k,
        )
    driver = SimDriver(trace, sim=sim,
                       cost=default_cost_model(
                           hbm_gbps=args.hbm_gbps, ici_gbps=args.ici_gbps,
                           tp=args.tp, comm_qtype=args.comm_qtype))
    report = driver.run()
    line = report_json(report)
    if args.output:
        from bigdl_tpu.utils.durability import atomic_write

        atomic_write(args.output,
                     lambda f: f.write((line + "\n").encode("utf-8")))
        print(f"wrote report to {args.output}", file=sys.stderr)
    print(line)


def cmd_lint(args):
    """graftlint: the AST-based invariant gate (docs/static-analysis.md).

        bigdl-tpu lint                     # whole bigdl_tpu package
        bigdl-tpu lint bigdl_tpu/serving   # a subtree / single file
        bigdl-tpu lint --rules WCT001,PAGE002
        bigdl-tpu lint --format github     # ::error CI annotations
        bigdl-tpu lint --write-baseline    # grandfather current findings
        bigdl-tpu lint --update-baseline   # drop stale, keep justifications

    Exit 0 = clean, 1 = non-baselined findings or stale baseline
    entries, 2 = config error. Deliberately jax-free: scripts/ci.sh
    --lint asserts jax never entered sys.modules during a run."""
    from bigdl_tpu.analysis import core as lint_core

    if args.list_rules:
        for c in lint_core.default_checks():
            print(f"{c.rule}  {c.description}")
        raise SystemExit(0)
    write_to = None
    if args.write_baseline:
        write_to = args.baseline or lint_core.DEFAULT_BASELINE
    raise SystemExit(lint_core.run(
        paths=args.paths or None,
        baseline_path=args.baseline,
        rules=args.rules.split(",") if args.rules else None,
        write_baseline_path=write_to,
        fmt=args.format,
        update_baseline=args.update_baseline,
    ))


def cmd_bench(args):
    model = _load(args.model, args.qtype)
    n_in, n_out = args.in_len, args.out_len
    ids = list(range(1, n_in + 1))
    # warm BOTH jit specializations (max_new_tokens is static) before
    # any timing, or the first-token run would include a compile
    model.generate([ids], max_new_tokens=1)
    model.generate([ids], max_new_tokens=n_out)
    t1 = time.time()
    model.generate([ids], max_new_tokens=1)
    first = time.time() - t1
    t0 = time.time()
    model.generate([ids], max_new_tokens=n_out)
    dt = max((time.time() - t0 - first) / max(n_out - 1, 1), 1e-5) * 1000
    print(json.dumps({
        "metric": "decode_latency", "value": round(dt, 2),
        "unit": "ms/token", "first_token_ms": round(first * 1000, 1),
        "protocol": f"in{n_in}-out{n_out}",
    }))


def main(argv=None):
    p = argparse.ArgumentParser(prog="bigdl-tpu")
    # -q works BOTH before the subcommand (top-level, original position)
    # and after it (documented position): the subparser copy defaults to
    # SUPPRESS so it never clobbers a top-level value
    p.add_argument("-q", "--qtype", default=None,
                   help="sym_int4 (HF default) / q4_k_m / ... ; gguf keeps "
                        "native formats unless set")
    qp = argparse.ArgumentParser(add_help=False)
    qp.add_argument("-q", "--qtype", default=argparse.SUPPRESS,
                    help=argparse.SUPPRESS)
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("convert", help="quantize + save_low_bit / gguf export",
                       parents=[qp])
    c.add_argument("model")
    c.add_argument("-o", "--output", required=True)
    c.add_argument("-f", "--format", choices=("low_bit", "gguf"),
                   default="low_bit",
                   help="low_bit: our reload format; gguf: llama.cpp file")
    c.add_argument("--gguf-qtype", default="q8_0",
                   # literal: keep CLI startup free of convert imports
                   # (must mirror gguf_export._GGML_FOR_QTYPE)
                   choices=("bf16", "f16", "f32", "q2_k", "q3_k", "q4_0",
                            "q4_k", "q5_k", "q6_k", "q8_0"),
                   help="gguf payload type")
    c.set_defaults(fn=cmd_convert)

    g = sub.add_parser("generate", help="one-shot generation", parents=[qp])
    g.add_argument("model")
    g.add_argument("-p", "--prompt", required=True)
    g.add_argument("-n", "--max-new-tokens", type=int, default=64)
    g.add_argument("-t", "--temperature", type=float, default=0.0)
    g.set_defaults(fn=cmd_generate)

    s = sub.add_parser("serve", help="OpenAI-compatible server", parents=[qp])
    s.add_argument("model")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--slots", type=int, default=8)
    s.add_argument("--max-len", type=int, default=2048)
    s.add_argument("--speculative", action="store_true",
                   help="in-engine speculative decoding (sym_int4 "
                        "self-draft; needs an unquantized model load)")
    s.add_argument("--draft-k", type=int, default=4)
    s.add_argument("--adaptive-draft", action="store_true",
                   help="steer draft length from recent acceptance "
                        "(ladder of compiled K programs)")
    s.add_argument("--embedder", default=None,
                   help="bert checkpoint dir: enables POST /v1/embeddings")
    s.add_argument("--truncate-prompts", action="store_true",
                   help="keep the tail of over-long prompts instead of "
                        "rejecting them with 400")
    s.add_argument("--logprobs-top-k", type=int, default=0,
                   help="serve OpenAI top_logprobs with up to N "
                        "alternatives per token")
    s.add_argument("--paged", action="store_true",
                   help="paged KV pool + radix prefix caching")
    s.add_argument("--prefill-chunk-tokens", type=int, default=None,
                   help="paged: interleave prompt prefill with decode "
                        "in chunks of at most N tokens, bounding the "
                        "running batch's stall to one chunk per step "
                        "(docs/serving.md §6; default: monolithic)")
    s.add_argument("--trace", action="store_true",
                   help="record request-lifecycle spans into a bounded "
                        "ring buffer (dump: `bigdl-tpu trace dump`, or "
                        "GET /debug/trace; docs/observability.md)")
    s.add_argument("--trace-capacity", type=int, default=65536,
                   help="span ring-buffer bound (newest kept)")
    s.add_argument("--request-log", default=None,
                   help="append one derived-timings JSONL record per "
                        "finished request (queue wait, TTFT, "
                        "time-per-output-token, preempted time)")
    s.add_argument("--adapter-dir", default=None,
                   help="multi-tenant LoRA: directory of <name>.npz "
                        "adapter artifacts; requests may then carry "
                        '"adapter": "<name>" and the /adapters '
                        "lifecycle endpoints come up (docs/serving.md §7)")
    s.add_argument("--adapter-budget-mb", type=int, default=None,
                   help="host-RAM budget for resident adapters; LRU "
                        "eviction above it (default: unbounded; "
                        "enables the registry even without "
                        "--adapter-dir — load via POST /adapters/load "
                        "with an explicit path)")
    s.add_argument("--adapters", action="append", default=None,
                   metavar="NAME[=PATH]",
                   help="preload + pin an adapter at startup "
                        "(repeatable; PATH defaults to "
                        "<adapter-dir>/NAME.npz)")
    s.add_argument("--comm-qtype", default="none",
                   choices=("none", "int8", "fp8_e4m3"),
                   help="multi-chip: quantize TP collectives to this "
                        "block-scaled wire format (parallel/"
                        "qcollectives.py; picked up by to_mesh(); "
                        "'none' = exact fp32/bf16 ICI traffic)")
    s.set_defaults(fn=cmd_serve)

    fw = sub.add_parser("fastchat-worker",
                        help="FastChat model-worker (register + heartbeat "
                             "+ worker_generate_stream)", parents=[qp])
    fw.add_argument("model")
    fw.add_argument("--controller-address", default=None,
                    help="FastChat controller URL, e.g. http://host:21001 "
                         "(omit to run unregistered)")
    fw.add_argument("--worker-address", default=None,
                    help="URL the controller should reach us at")
    fw.add_argument("--model-names", default=None,
                    help="comma-separated names to register")
    fw.add_argument("--host", default="127.0.0.1")
    fw.add_argument("--port", type=int, default=21002)
    fw.add_argument("--slots", type=int, default=8)
    fw.add_argument("--max-len", type=int, default=2048)
    fw.add_argument("--paged", action="store_true")
    fw.set_defaults(fn=cmd_fastchat_worker)

    ft = sub.add_parser("fetch-iq-tables",
                        help="download + cache the llama.cpp IQ-quant "
                             "codebook grids (one-time, per machine)")
    # default=None: resolved in cmd_fetch_iq_tables, keeping parser
    # build free of quant imports (file convention)
    ft.add_argument("--url", default=None,
                    help="override the llama.cpp ggml-common.h URL")
    ft.set_defaults(fn=cmd_fetch_iq_tables)

    ti = sub.add_parser("txt2img",
                        help="Stable Diffusion text-to-image (diffusers "
                             "checkpoint dir, fully on-device)",
                        parents=[qp])
    ti.add_argument("model", help="local diffusers pipeline directory")
    ti.add_argument("-p", "--prompt", required=True)
    ti.add_argument("--negative", default=None)
    ti.add_argument("-o", "--output", default="out.png")
    ti.add_argument("--size", type=int, default=512)
    ti.add_argument("--steps", type=int, default=20)
    ti.add_argument("--guidance", type=float, default=7.5)
    ti.add_argument("--seed", type=int, default=0)
    ti.set_defaults(fn=cmd_txt2img)

    ch = sub.add_parser("chat", help="interactive chat REPL", parents=[qp])
    ch.add_argument("model")
    ch.add_argument("-n", "--max-new-tokens", type=int, default=256)
    ch.add_argument("-t", "--temperature", type=float, default=0.7)
    ch.add_argument("--system", default=None, help="system prompt")
    ch.add_argument("--max-len", type=int, default=2048,
                   help="session KV cache length")
    ch.add_argument("--streaming-window", type=int, default=None,
                   help="attention-sink window: unbounded conversation "
                        "in constant memory")
    ch.add_argument("--streaming-sink", type=int, default=4)
    ch.add_argument("--adapter", default=None,
                    help="LoRA adapter artifact (.npz) merged into the "
                         "model for this chat session")
    ch.set_defaults(fn=cmd_chat)

    v = sub.add_parser(
        "verify",
        help="full integrity + numerical validation of a low-bit or "
             "train checkpoint; exit 1 on any finding",
    )
    v.add_argument("path", help="save_low_bit dir, train .npz, or a "
                                "rotation dir of ckpt-*.npz")
    v.set_defaults(fn=cmd_verify)

    ts = sub.add_parser(
        "train-status",
        help="training-run health: last-good step, checkpoint rotation "
             "inventory, supervisor event-log tail (exit 1 when no "
             "checkpoint is loadable)",
    )
    ts.add_argument("ckpt_dir", help="the trainer's --ckpt-dir")
    ts.add_argument("--events", type=int, default=15,
                    help="event-log tail length")
    ts.set_defaults(fn=cmd_train_status)

    tr = sub.add_parser(
        "trace",
        help="serving observability: dump a live server's span ring "
             "buffer (Perfetto-loadable), summarize a trace file into "
             "a latency table, or start/stop a jax.profiler window",
    )
    tr.add_argument("action",
                    choices=("dump", "summarize", "profile-start",
                             "profile-stop"))
    tr.add_argument("target",
                    help="server base URL (dump/profile-*) or a dumped "
                         "trace .json file (summarize)")
    tr.add_argument("-o", "--output", default="trace.json",
                    help="dump: output file")
    tr.add_argument("--logdir", default=None,
                    help="profile-start: jax.profiler output directory "
                         "on the SERVER's filesystem")
    tr.set_defaults(fn=cmd_trace)

    ad = sub.add_parser(
        "adapters",
        help="multi-tenant LoRA lifecycle: list/load/unload against a "
             "live server, or inspect a local adapter artifact "
             "(docs/serving.md §7)",
    )
    ad.add_argument("action",
                    choices=("list", "load", "unload", "inspect"))
    ad.add_argument("target",
                    help="server base URL (list/load/unload) or an "
                         "adapter .npz path (inspect)")
    ad.add_argument("name", nargs="?", default=None,
                    help="adapter name (load/unload)")
    ad.add_argument("--path", default=None,
                    help="load: explicit artifact path (default: "
                         "<adapter-dir>/<name>.npz on the server)")
    ad.add_argument("--pin", action="store_true",
                    help="load: exempt from LRU eviction")
    ad.set_defaults(fn=cmd_adapters)

    sv = sub.add_parser(
        "simserve",
        help="simulated-clock serving benchmark: real engine + virtual "
             "clock + roofline cost model, zero devices (one JSON "
             "report line; docs/benchmarking.md)",
    )
    sv.add_argument("--trace", default="poisson",
                    # literal: keep CLI startup free of sim/jax imports
                    # (must mirror sim/traces.TRACE_NAMES)
                    choices=("poisson", "bursty", "prefix-heavy",
                             "overload", "adapter-zipf", "speculative",
                             "adapter-spec"),
                    help="named trace mix (overload exercises "
                         "preemption AND shed; adapter-zipf the "
                         "multi-tenant LoRA registry churn; adapter-spec "
                         "adapters THROUGH speculative decode under a "
                         "tight unified page pool)")
    sv.add_argument("--speculative", action="store_true",
                    help="run the mix through draft+verify speculative "
                         "rounds regardless of its scenario default "
                         "(adapter mixes verify with the adapter "
                         "applied)")
    sv.add_argument("--draft-k", type=int, default=None,
                    help="draft length for --speculative (implies it "
                         "when set; default: the scenario's draft_k)")
    sv.add_argument("--trace-file", default=None,
                    help="replay a banked trace JSONL instead of "
                         "generating one")
    sv.add_argument("--seed", type=int, default=0,
                    help="trace-generator seed (same seed = "
                         "byte-identical trace and report)")
    sv.add_argument("--hbm-gbps", type=float, default=None,
                    help="cost-model calibration knob: achievable HBM "
                         "GB/s of the modeled chip (default v5e-class)")
    sv.add_argument("--ici-gbps", type=float, default=None,
                    help="cost-model calibration knob: per-link ICI "
                         "GB/s for the modeled TP ring (default "
                         "v5e-class; only matters with --tp > 1)")
    sv.add_argument("--tp", type=int, default=None,
                    help="model the per-layer TP all-reduce for this "
                         "ring size (additive comm overhead; "
                         "default 1 = no collective term)")
    sv.add_argument("--comm-qtype", default=None,
                    choices=("none", "int8", "fp8_e4m3"),
                    help="price the modeled all-reduce at this "
                         "block-scaled wire format instead of fp32 "
                         "(benchmark/roofline.all_reduce_cost)")
    sv.add_argument("--save-trace", default=None,
                    help="bank the generated arrival trace as crc'd "
                         "JSONL")
    sv.add_argument("-o", "--output", default=None,
                    help="also write the report JSON to a file "
                         "(atomic)")
    sv.set_defaults(fn=cmd_simserve)

    ln = sub.add_parser(
        "lint",
        help="graftlint: AST invariant checks over bigdl_tpu/ (clock "
             "injection, atomic writes, fault points, lock discipline, "
             "metrics drift, donation, journal crc, plus the v2 "
             "interprocedural families: PAGE page-leak proofs, LCK "
             "lock-order cycles, DSP dispatch consistency; exit 1 on "
             "any non-baselined finding — docs/static-analysis.md)",
    )
    ln.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the installed "
                         "bigdl_tpu package)")
    ln.add_argument("--baseline", default=None,
                    help="baseline JSON (default: the checked-in "
                         "bigdl_tpu/analysis/baseline.json)")
    ln.add_argument("--rules", default=None,
                    help="comma-separated rule subset, e.g. WCT001,ATW001")
    ln.add_argument("--write-baseline", action="store_true",
                    help="record current findings as the new baseline "
                         "(each entry then needs a justification edit)")
    ln.add_argument("--update-baseline", action="store_true",
                    help="regenerate the baseline in place: stale "
                         "entries drop, surviving justifications carry "
                         "over")
    ln.add_argument("--format", choices=("human", "json", "github"),
                    default="human",
                    help="output format (github = ::error annotation "
                         "lines for CI inline comments)")
    ln.add_argument("--list-rules", action="store_true")
    ln.set_defaults(fn=cmd_lint)

    b = sub.add_parser("bench", help="quick decode-latency check", parents=[qp])
    b.add_argument("model")
    def _min2(v):
        iv = int(v)
        if iv < 2:  # one timed token can't separate decode from first-token
            raise argparse.ArgumentTypeError("--out-len must be >= 2")
        return iv

    b.add_argument("--in-len", type=int, default=32)
    b.add_argument("--out-len", type=_min2, default=32)
    b.set_defaults(fn=cmd_bench)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
