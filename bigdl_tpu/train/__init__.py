"""Finetuning on quantized bases (reference L6: qlora.py, relora.py,
lisa.py — SURVEY.md §2.2)."""

from bigdl_tpu.train.qlora import (
    init_lora,
    make_train_step,
    merge_lora,
    next_token_loss,
)

__all__ = ["init_lora", "make_train_step", "merge_lora", "next_token_loss"]
