"""Finetuning on quantized bases (reference L6: qlora.py, relora.py,
lisa.py, DPO example recipe — SURVEY.md §2.2)."""

from bigdl_tpu.train.qlora import (
    init_lora,
    make_train_step,
    merge_lora,
    next_token_loss,
)
from bigdl_tpu.train.recipes import (
    ReLoRASchedule,
    ReLoRAState,
    apply_layer_mask,
    make_full_train_step,
    relora_reset,
    sample_lisa_mask,
)
from bigdl_tpu.train.checkpoint import (
    inspect_train_checkpoint,
    inspect_train_checkpoints_dir,
    list_train_checkpoints,
    load_latest_train_state,
    load_train_state,
    save_train_state,
    save_train_state_rotating,
)
from bigdl_tpu.train.supervisor import (
    SupervisorAbort,
    SupervisorConfig,
    TrainFaultInjector,
    TrainSupervisor,
)
from bigdl_tpu.train.dpo import dpo_loss, make_dpo_step, sequence_logprob
from bigdl_tpu.train.galore import GaLoreState, galore

__all__ = [
    "init_lora",
    "make_train_step",
    "merge_lora",
    "next_token_loss",
    "ReLoRASchedule",
    "ReLoRAState",
    "apply_layer_mask",
    "make_full_train_step",
    "relora_reset",
    "sample_lisa_mask",
    "dpo_loss",
    "make_dpo_step",
    "sequence_logprob",
    "GaLoreState",
    "galore",
    "save_train_state",
    "load_train_state",
    "save_train_state_rotating",
    "load_latest_train_state",
    "list_train_checkpoints",
    "inspect_train_checkpoint",
    "inspect_train_checkpoints_dir",
    "TrainSupervisor",
    "SupervisorConfig",
    "SupervisorAbort",
    "TrainFaultInjector",
]
