"""Direct Preference Optimization.

The reference ships DPO as an example recipe over TRL
(`python/llm/example/GPU/LLM-Finetuning/DPO` in /root/reference — QLoRA
base + TRL's DPOTrainer); here the loss itself is implemented natively so
the same jitted-step machinery covers preference tuning: the policy is
(frozen low-bit base + LoRA), the reference model is the SAME base with
adapters disabled — no second model copy in HBM (TRL's
`ref_model=None` peft trick, done structurally).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from bigdl_tpu.models.config import ModelConfig


def sequence_logprob(
    config: ModelConfig,
    forward_fn: Callable,
    params: dict,
    lora: Optional[dict],
    tokens: jax.Array,  # [B, T]
    loss_mask: jax.Array,  # [B, T] 1.0 on completion tokens (targets)
) -> jax.Array:
    """[B] sum of per-token log p(target) over masked positions."""
    logits, _ = forward_fn(config, params, tokens[:, :-1], None, lora=lora)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    tok_lp = jnp.take_along_axis(logp, tgt[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return jnp.sum(tok_lp * loss_mask[:, 1:].astype(jnp.float32), axis=-1)


def dpo_loss(
    config: ModelConfig,
    forward_fn: Callable,
    params: dict,
    lora: dict,
    chosen: jax.Array,  # [B, T]
    chosen_mask: jax.Array,
    rejected: jax.Array,  # [B, T]
    rejected_mask: jax.Array,
    beta: float = 0.1,
    label_smoothing: float = 0.0,
) -> tuple[jax.Array, dict]:
    """Standard DPO: -log σ(β[(π_c - π_r) - (ref_c - ref_r)]).

    The reference policy is the base model with lora=None — gradients flow
    only through the adapter branch, exactly TRL's peft shortcut.
    """
    pol_c = sequence_logprob(config, forward_fn, params, lora, chosen, chosen_mask)
    pol_r = sequence_logprob(config, forward_fn, params, lora, rejected, rejected_mask)
    ref_c = jax.lax.stop_gradient(
        sequence_logprob(config, forward_fn, params, None, chosen, chosen_mask)
    )
    ref_r = jax.lax.stop_gradient(
        sequence_logprob(config, forward_fn, params, None, rejected, rejected_mask)
    )
    logits = beta * ((pol_c - pol_r) - (ref_c - ref_r))
    loss = (
        -jax.nn.log_sigmoid(logits) * (1 - label_smoothing)
        - jax.nn.log_sigmoid(-logits) * label_smoothing
    )
    aux = {
        "reward_margin": jnp.mean(logits) / beta,
        "accuracy": jnp.mean((logits > 0).astype(jnp.float32)),
        "policy_chosen_logp": jnp.mean(pol_c),
        "policy_rejected_logp": jnp.mean(pol_r),
    }
    return jnp.mean(loss), aux


def make_dpo_step(
    config: ModelConfig,
    forward_fn: Callable,
    optimizer: optax.GradientTransformation,
    beta: float = 0.1,
):
    """step(params, lora, opt_state, chosen, chosen_mask, rejected,
    rejected_mask) -> (lora, opt_state, loss, aux)."""

    def step(params, lora, opt_state, chosen, chosen_mask, rejected, rejected_mask):
        scale = lora["scale"]

        def loss_fn(layers):
            return dpo_loss(
                config, forward_fn, params, {"layers": layers, "scale": scale},
                chosen, chosen_mask, rejected, rejected_mask, beta=beta,
            )

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            lora["layers"]
        )
        updates, opt_state = optimizer.update(grads, opt_state, lora["layers"])
        layers = optax.apply_updates(lora["layers"], updates)
        return {"layers": layers, "scale": scale}, opt_state, loss, aux

    return step
