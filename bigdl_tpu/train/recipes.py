"""Finetuning recipes beyond plain QLoRA.

TPU-native re-designs of the reference's training extras:
- ReLoRA (`transformers/relora.py:64-150` periodic merge-and-reset +
  optimizer-state pruning `:128`): high-rank updates from a sequence of
  low-rank phases.
- LISA (`transformers/lisa.py:23-81` DynamicLayerActivationCallback):
  full-weight finetuning with a random subset of layers unfrozen per
  interval. With layers stacked on a leading axis, (un)freezing is a
  per-layer gradient mask — no module surgery.
- Full finetune step for dense models (the reference delegates this to
  HF Trainer + deepspeed; here it is the same jitted step pattern as
  QLoRA, over the whole param tree).

The reference hooks these into HF Trainer callbacks; here each recipe is
a pure function over (params, opt_state) plus a small schedule object the
host loop consults — no trainer framework required.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.train.qlora import init_lora, merge_lora, next_token_loss


# ---------------------------------------------------------------------------
# ReLoRA
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ReLoRAState:
    params: dict  # base (merged so far)
    lora: dict
    opt_state: optax.OptState
    resets: int = 0


def relora_reset(
    config: ModelConfig,
    state: ReLoRAState,
    optimizer: optax.GradientTransformation,
    key: jax.Array,
    rank: int = 8,
    alpha: float = 16.0,
    requantize: Optional[str] = None,
) -> ReLoRAState:
    """Merge the current adapters into the base, re-init them, and prune
    the optimizer state (reference relora.py:64-150; the pruning at :128
    zeroes optimizer moments so each phase starts cold)."""
    targets = tuple(state.lora["layers"].keys())
    merged = merge_lora(state.params, state.lora, requantize=requantize)
    fresh = init_lora(config, key, rank=rank, alpha=alpha, targets=targets)
    opt_state = optimizer.init(fresh["layers"])
    return ReLoRAState(
        params=merged, lora=fresh, opt_state=opt_state, resets=state.resets + 1
    )


class ReLoRASchedule:
    """Host-side: call should_reset(step) each step; reset_every in steps
    (the reference's relora_steps)."""

    def __init__(self, reset_every: int, warmup: int = 0):
        self.reset_every = reset_every
        self.warmup = warmup

    def should_reset(self, step: int) -> bool:
        return (
            step > self.warmup
            and self.reset_every > 0
            and step % self.reset_every == 0
        )


# ---------------------------------------------------------------------------
# LISA
# ---------------------------------------------------------------------------

def sample_lisa_mask(
    key: jax.Array, n_layers: int, n_active: int
) -> jax.Array:
    """[L] float mask with exactly n_active ones (the layers that train
    this interval) — reference lisa.py:23-81 `switch_active_layers`."""
    perm = jax.random.permutation(key, n_layers)
    return (perm < n_active).astype(jnp.float32)


def apply_layer_mask(grads: dict, mask: jax.Array) -> dict:
    """Zero the gradient of frozen layers. Works on any tree whose layer
    leaves are stacked [L, ...]; non-stacked leaves (embed/head/norms)
    pass through untouched."""
    L = mask.shape[0]

    def f(g):
        if g.ndim >= 1 and g.shape[0] == L:
            return g * mask.reshape((L,) + (1,) * (g.ndim - 1)).astype(g.dtype)
        return g

    return jax.tree.map(f, grads)


# ---------------------------------------------------------------------------
# Full finetune (dense weights)
# ---------------------------------------------------------------------------

def make_full_train_step(
    config: ModelConfig,
    forward_fn: Callable,
    optimizer: optax.GradientTransformation,
    train_embed: bool = True,
):
    """step(params, opt_state, tokens, loss_mask, layer_mask|None) ->
    (params, opt_state, loss). layer_mask is the LISA per-layer mask;
    None trains everything. Quantized (QTensor) leaves are not supported —
    full finetune needs dense weights (use QLoRA for low-bit bases)."""

    def step(params, opt_state, tokens, loss_mask, layer_mask=None):
        def loss_fn(p):
            return next_token_loss(config, forward_fn, p, None, tokens, loss_mask)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        if layer_mask is not None:
            grads["layers"] = apply_layer_mask(grads["layers"], layer_mask)
        if not train_embed:
            grads = dict(grads)
            grads["embed"] = jnp.zeros_like(grads["embed"])
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return step
