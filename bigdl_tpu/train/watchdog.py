"""Hung-step watchdog: failure DETECTION for long training jobs.

The failure-recovery story so far covers the state half (atomic
checkpoint/resume, train/checkpoint.py) but not detection: on a
multi-host job a single lost peer leaves every other process blocked
inside an XLA collective forever — no exception, no timeout, a silently
idle pod bill (the reference's MPI jobs hang identically; their k8s
spec only restarts on process EXIT,
reference docker/llm/finetune/lora/cpu/kubernetes/templates/
ipex-llm-lora-finetuning-job.yaml:7-54).

`StepWatchdog` converts a hang into an exit the orchestrator can see: a
daemon thread checks progress beats; if no step completes within
`timeout_s` it logs a diagnosis and hard-exits the process (os._exit —
a blocked collective never returns to Python, so SystemExit/signals
through the main thread cannot fire). The container restart policy then
relaunches the job, which resumes from the last atomic checkpoint.

Usage (the train recipes call this when BIGDL_TPU_WATCHDOG_S is set):

    wd = StepWatchdog(timeout_s=1800)
    for step in range(...):
        state = train_step(state, batch)
        jax.block_until_ready(state)   # beat only counts finished work
        wd.beat(step)
    wd.stop()
"""

from __future__ import annotations

import os
import sys
import threading
import time


class StepWatchdog:
    """Exit the process (code 42) if no beat arrives within timeout_s.

    The check thread is a daemon: a normally-finishing job needs no
    explicit stop() (but calling it is cheap and makes intent clear).
    `on_timeout` (testing hook) replaces the default os._exit.
    """

    EXIT_CODE = 42  # distinct, grep-able "watchdog fired" exit status

    def __init__(self, timeout_s: float, check_interval_s: float | None = None,
                 on_timeout=None):
        if timeout_s <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        self.timeout_s = timeout_s
        self._interval = check_interval_s or min(timeout_s / 4, 30.0)
        self._on_timeout = on_timeout or self._default_timeout
        self._last_beat = time.monotonic()
        self._last_step = -1
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="bigdl-tpu-watchdog", daemon=True
        )
        self._thread.start()

    def beat(self, step: int | None = None) -> None:
        self._last_beat = time.monotonic()
        if step is not None:
            self._last_step = step

    def stop(self) -> None:
        self._stop.set()

    # ------------------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            idle = time.monotonic() - self._last_beat
            if idle > self.timeout_s:
                self._on_timeout(idle)
                return

    def _default_timeout(self, idle: float) -> None:
        pid = os.environ.get("BIGDL_TPU_PROC_ID", "?")
        print(
            f"[bigdl-tpu watchdog] no training step completed for "
            f"{idle:.0f}s (> {self.timeout_s:.0f}s) on process {pid}; "
            f"last finished step={self._last_step}. A lost peer leaves "
            "XLA collectives blocked forever — exiting "
            f"{self.EXIT_CODE} so the orchestrator restarts the job "
            "from the last checkpoint.",
            file=sys.stderr, flush=True,
        )
        sys.stderr.flush()
        os._exit(self.EXIT_CODE)  # collectives never return; exit hard


def timeout_from_env() -> float | None:
    """The BIGDL_TPU_WATCHDOG_S timeout, or None when unset/disabled.
    "0", negative, or malformed values DISABLE with a warning — a
    config typo must not crash-loop a 16-host job at startup. Callers
    that own their own watchdog (train/supervisor.py) read this instead
    of from_env() so no throwaway check thread is ever started."""
    v = os.environ.get("BIGDL_TPU_WATCHDOG_S")
    if not v:
        return None
    try:
        timeout = float(v)
    except ValueError:
        timeout = 0.0
    if timeout <= 0:
        print(f"[bigdl-tpu watchdog] BIGDL_TPU_WATCHDOG_S={v!r} is not a "
              "positive number; watchdog disabled", file=sys.stderr)
        return None
    return timeout


def from_env() -> StepWatchdog | None:
    """BIGDL_TPU_WATCHDOG_S=<seconds> enables the watchdog (the deploy/
    job specs set it alongside the restart policy)."""
    timeout = timeout_from_env()
    return None if timeout is None else StepWatchdog(timeout)
