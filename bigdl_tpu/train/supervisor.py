"""Anomaly-guarded training supervisor: the resilience layer between a
jitted train step and a long-lived multi-host job.

The reference runs finetuning as bare MPI k8s jobs (SURVEY §2.3): one
NaN step corrupts the optimizer state for good, a preempted pod loses
everything since the last manual save, and a lost peer hangs every
other host inside a collective forever. Low-bit training makes the
first failure routine — quantized grads overflow/NaN far more readily
("Training Transformers with 4-bit Integers", arxiv 2306.11987). This
module is the training-side counterpart of what PR 6/7 built for
serving and storage:

- **Anomaly guard** — after every step the loss (and, when the step
  exposes it, the global grad-norm) is checked host-side for NaN/inf,
  plus an EMA spike detector (loss > `spike_factor` x EMA after
  warmup). An anomalous step is *skipped*: the freshly computed
  lora/opt_state are discarded and the previous ones — bit-identical,
  never donated — carry forward. The skip/continue verdict AND the
  preemption flag ride one `parallel/health.consensus_any` reduction
  per step, so on a multi-host job every rank takes the same branch
  (a rank-local decision would fork the SPMD program state) and one
  rank's SIGTERM exits the whole job at the same step boundary.
- **Rollback** — `max_consecutive_anomalies` anomalies in a row mean
  the *state* is poisoned, not the batch: the supervisor reloads the
  last good rotating checkpoint (`load_latest_train_state`) and
  resumes from its step. `max_rollbacks` bounds the retry loop.
- **Preemption safety** — SIGTERM/SIGINT set a flag; at the next step
  boundary the supervisor writes an emergency rotating checkpoint and
  exits with the distinct code :data:`EXIT_PREEMPTED` (43). Resume is
  *unconditional* on start: a restarted pod picks up the newest
  loadable checkpoint and continues bit-exactly.
- **Hung-step watchdog** — `train/watchdog.StepWatchdog` beats on every
  *finished* step (the host-side loss fetch synchronizes); a wedged
  DCN collective becomes exit 42 with a diagnostic instead of an idle
  pod bill.
- **Structured events** — every anomaly/skip/rollback/checkpoint/
  preempt/abort appends a crc-suffixed JSONL record under the
  checkpoint dir (`bigdl-tpu train-status` tails it), and process-wide
  counters render on /metrics (`serving/metrics.py`).

Every path is driven on CPU by :class:`TrainFaultInjector` (the same
arm/fire discipline as `serving/faults.FaultInjector`):

==================  ====================================================
point               effect when armed
==================  ====================================================
``nan_loss``        the next step's host-side loss reads as NaN
``nan_grad``        the next step's host-side grad-norm reads as NaN
``loss_spike``      the next step's loss reads as spike_factor x EMA x 4
``hang_step``       the step stalls ``seconds=`` before running (drives
                    the watchdog). payload: ``seconds=float``
``preempt_signal``  as if SIGTERM arrived before the step boundary
``rank_drop``       the heartbeat loses ``rank=`` (default: last rank)
                    — drives the RankDropError abort path
==================  ====================================================

Usage (deploy/multihost_qlora.py is the production caller)::

    sup = TrainSupervisor(
        lambda lora, opt, *b: step_j(params, lora, opt, *b),
        ckpt_dir=ckpt_dir, lora=lora, opt_state=opt_state,
        rng=jax.random.PRNGKey(42),
        config=SupervisorConfig(save_every=100, step_timeout_s=1800),
        is_chief=(jax.process_index() == 0),
    )
    sup.resume()               # unconditional auto-resume
    state = sup.run(batch_fn, total_steps)

The wrapped step fn must NOT donate lora/opt_state at its jit call
site: the skip path keeps the previous buffers alive for exactly one
step (the price of an untouched optimizer state after a NaN).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import threading
import time
from typing import Any, Callable, Optional

from bigdl_tpu.serving.faults import FaultInjector
from bigdl_tpu.serving.metrics import (
    TRAIN_ANOMALIES,
    TRAIN_EMERGENCY_CHECKPOINTS,
    TRAIN_ROLLBACKS,
    TRAIN_STEP_SECONDS,
    TRAIN_STEPS_SKIPPED,
    TRAIN_WATCHDOG_ABORTS,
)
from bigdl_tpu.train.checkpoint import (
    load_latest_train_state,
    save_train_state_rotating,
)
from bigdl_tpu.train.watchdog import StepWatchdog

POINTS = ("nan_loss", "nan_grad", "loss_spike", "hang_step",
          "preempt_signal", "rank_drop")

#: distinct exit codes the orchestrator's restart policy can tell apart
EXIT_WATCHDOG = StepWatchdog.EXIT_CODE  # 42: hung step, restart+resume
EXIT_PREEMPTED = 43  # emergency checkpoint written, restart+resume


class TrainFaultInjector(FaultInjector):
    """Seedable injector for the training loop — reuses the serving
    harness's class-attr `points` discipline (arm/disarm/fire, seen/
    fired counters, deterministic times/after/prob arming)."""

    points = POINTS


class SupervisorAbort(RuntimeError):
    """Terminal, structured abort: the supervisor refuses to continue
    (rank drop, rollback loop) and says exactly why — never a silent
    hang, never a bare stack trace from deep inside a collective."""

    def __init__(self, kind: str, step: int, detail: str):
        self.kind = kind
        self.step = step
        self.detail = detail
        super().__init__(
            f"training aborted at step {step} [{kind}]: {detail}"
        )


@dataclasses.dataclass
class SupervisorConfig:
    save_every: int = 100        # rotating-checkpoint cadence (chief)
    keep_last: int = 3           # rotation retention
    verify: str = "fast"         # resume/rollback load verification
    spike_factor: float = 10.0   # loss > factor * EMA -> anomaly
    ema_beta: float = 0.9        # EMA smoothing for the spike baseline
    warmup_steps: int = 5        # applied steps before the spike guard arms
    max_consecutive_anomalies: int = 3  # K -> rollback
    max_rollbacks: int = 3       # rollbacks before SupervisorAbort
    step_timeout_s: Optional[float] = None  # watchdog (None = off)
    heartbeat_every: int = 10    # steps between cross-host health checks
    event_log: str = "supervisor_events.jsonl"  # under ckpt_dir (chief)


class EventLog:
    """Append-only JSONL event stream, one `{ts, step, kind, ...}` per
    line in the serving journal's exact tab+crc32 wire discipline
    (serving/journal.crc_line — interior rot in a months-old log is
    detectable, and the two formats cannot drift). Losing events must
    never kill training: every write failure degrades to a
    warning-free no-op.

    An optional `tracer` (obs/tracing.TraceRecorder) mirrors every
    event as an instant on the trainer track — the same recorder and
    trace format the serving engine uses, so a training run and a
    serving run open identically in Perfetto (docs/observability.md)."""

    def __init__(self, path: Optional[str], tracer: Optional[Any] = None,
                 clock: Callable[[], float] = time.time):
        self.path = path
        self.tracer = tracer
        # the log line's ts is OPERATOR time (wall by default, injectable
        # for simulated runs; graftlint WCT001) — the mirrored trace
        # instant below stays in the tracer's own clock domain
        self._clock = clock
        self._f = None
        if path is not None:
            try:
                os.makedirs(os.path.dirname(os.path.abspath(path)),
                            exist_ok=True)
                self._f = open(path, "a", encoding="utf-8")
            except OSError:  # pragma: no cover - read-only ckpt mount
                self._f = None

    def emit(self, kind: str, step: int, **detail: Any) -> None:
        ts = round(self._clock(), 3)
        if self.tracer is not None and self.tracer.enabled:
            # the mirrored instant is stamped in the TRACER's clock
            # domain (the log line keeps wall time for operators): a
            # simulated-clock tracer must not get wall-epoch instants
            # billions of seconds away from its train.step spans
            self.tracer.instant(kind, ts=self.tracer.now(), tid=0,
                                cat="train", step=int(step), **detail)
        if self._f is None:
            return
        from bigdl_tpu.serving.journal import crc_line

        body = json.dumps(
            {"ts": ts, "step": int(step), "kind": kind, **detail},
            separators=(",", ":"),
        )
        try:
            self._f.write(crc_line(body) + "\n")
            self._f.flush()
        except OSError:  # pragma: no cover
            pass

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None

    @staticmethod
    def tail(path: str, n: int = 20) -> list:
        """Last `n` decodable events (crc-mismatched / torn lines are
        skipped — same tolerance as the serving journal's scan, via the
        same split_crc_line codec)."""
        from bigdl_tpu.serving.journal import split_crc_line

        if not os.path.exists(path):
            return []
        out = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                body, ok = split_crc_line(line)
                if ok is False:
                    continue  # interior bit rot: skip, keep tailing
                try:
                    out.append(json.loads(body))
                except json.JSONDecodeError:
                    continue
        return out[-n:]


@dataclasses.dataclass
class StepReport:
    """What one supervised step did (the `on_step` hook's argument)."""

    step: int            # the step index this report is about
    loss: float
    grad_norm: Optional[float]
    skipped: bool        # anomaly: update discarded, state untouched
    reasons: tuple       # () when clean; ("nan_loss", ...) when skipped
    seconds: float       # wall-clock of the step (incl. loss fetch)


class TrainSupervisor:
    """Wraps `step_fn(lora, opt_state, *batch) -> (lora, opt_state,
    loss[, grad_norm])` — the shape every recipe factory in train/
    (qlora / dpo / galore / recipes) produces once the caller closes
    over its frozen params — with the full resilience layer described
    in the module docstring. State (lora, opt_state, rng, step) lives
    ON the supervisor between calls; `run` drives the loop."""

    def __init__(
        self,
        step_fn: Callable,
        *,
        ckpt_dir: str,
        lora: dict,
        opt_state: Any,
        rng: Any,
        config: Optional[SupervisorConfig] = None,
        faults: Optional[TrainFaultInjector] = None,
        is_chief: bool = True,
        process_index: int = 0,
        health=None,  # parallel/health.HealthMonitor (default-built)
        on_watchdog_timeout: Optional[Callable] = None,  # tests
        exit_fn: Optional[Callable] = None,  # tests: replace sys.exit
        tracer=None,  # obs/tracing.TraceRecorder: per-step "train.step"
        # spans + every EventLog event mirrored as trace instants, in
        # the serving engine's exact trace format
        clock: Callable[[], float] = time.monotonic,  # step-duration
        # timing (watchdog beats, TRAIN_STEP_SECONDS); injectable like
        # the serving engine's clock= (graftlint WCT001)
        wall_clock: Callable[[], float] = time.time,  # epoch-domain ts
        # for the EventLog lines (durations and epochs are different
        # clock domains — a simulated run injects both)
        fused_backward: Optional[bool] = None,  # which dx path step_fn
        # was traced with (train/qlora.make_train_step's knob): recorded
        # in the EventLog at run start so loss curves compared across
        # the fused/remat flip carry their provenance. None = the caller
        # didn't say (pre-knob step_fn); nothing is recorded.
    ):
        from bigdl_tpu.parallel.health import HealthMonitor

        self.step_fn = step_fn
        self.ckpt_dir = ckpt_dir
        self.config = config or SupervisorConfig()
        if self.config.save_every < 1:
            raise ValueError(
                f"save_every must be >= 1, got {self.config.save_every}"
            )
        self.lora = lora
        self.opt_state = opt_state
        self.rng = rng
        self.step = 0
        # resume/rollback templates: the INITIAL trees define the pytree
        # structure every checkpoint must unflatten onto
        self._like_lora = lora
        self._like_opt_state = opt_state
        self.is_chief = is_chief
        self.process_index = process_index
        self._faults = faults if faults is not None else _NULL_TRAIN_INJECTOR
        self.health = health if health is not None else HealthMonitor(
            process_index=process_index, faults=self._faults,
        )
        self._exit = exit_fn or sys.exit
        self._clock = clock
        self._on_watchdog_timeout = on_watchdog_timeout
        self._ema: Optional[float] = None
        self._applied_steps = 0       # spike-guard warmup counter
        self._consecutive_anomalies = 0
        self.rollbacks = 0
        self._preempt_flag = threading.Event()
        self._prev_handlers: dict = {}
        # chief writes supervisor_events.jsonl; other ranks get a
        # rank-suffixed sibling so a non-chief abort still leaves a trace
        name = self.config.event_log
        if not is_chief:
            root, ext = os.path.splitext(name)
            name = f"{root}.r{process_index}{ext or '.jsonl'}"
        self.tracer = tracer
        self.fused_backward = fused_backward
        self.events = EventLog(os.path.join(ckpt_dir, name),
                               tracer=tracer, clock=wall_clock)
        self._wd: Optional[StepWatchdog] = None
        if self.config.step_timeout_s is not None:
            self._wd = StepWatchdog(
                self.config.step_timeout_s,
                on_timeout=self._watchdog_fired,
            )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def resume(self) -> int:
        """Unconditional auto-resume: adopt the newest loadable rotated
        checkpoint (corrupt candidates are skipped by
        `load_latest_train_state` with the verify-failure counter
        bumped). Also seeds a step-0 baseline checkpoint when the dir
        is empty, so an early rollback always has a target. Returns the
        start step (0 when starting fresh)."""
        state = load_latest_train_state(
            self.ckpt_dir,
            like_lora=self._like_lora,
            like_opt_state=self._like_opt_state,
            verify=self.config.verify,
        )
        if state is not None:
            self.lora = state["lora"]
            self.opt_state = state["opt_state"]
            self.rng = state["rng"]
            self.step = int(state["step"])
            self.events.emit("resume", self.step, path=state["path"])
        elif self.is_chief:
            self._save(kind="baseline")
        return self.step

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> preempt flag (k8s sends SIGTERM, then
        SIGKILL after terminationGracePeriodSeconds — the emergency
        save must fit that window). Main-thread only; a second signal
        falls through to the previous handler so a stuck save is still
        interruptible."""
        if threading.current_thread() is not threading.main_thread():
            return  # signal.signal would raise; tests run in workers

        def _handler(signum, frame):
            self._preempt_flag.set()
            prev = self._prev_handlers.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)

        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev_handlers[sig] = signal.signal(sig, _handler)

    def request_preemption(self) -> None:
        """Programmatic SIGTERM equivalent (thread-safe)."""
        self._preempt_flag.set()

    def close(self) -> None:
        if self._wd is not None:
            self._wd.stop()
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):  # pragma: no cover
                pass
        self._prev_handlers.clear()
        self.events.close()

    # ------------------------------------------------------------------
    # the supervised loop
    # ------------------------------------------------------------------

    def run(
        self,
        batch_fn: Callable[[int], tuple],
        total_steps: int,
        on_step: Optional[Callable[[StepReport], None]] = None,
    ) -> dict:
        """Drive training to `total_steps`. `batch_fn(step)` returns the
        step args after lora/opt_state (a deterministic-by-step fn makes
        skip/rollback replays exact; a stream that ignores `step` is
        fine for stochastic data). Returns the final state dict."""
        if self.fused_backward is not None:
            # one provenance event per run, not per step: `bigdl-tpu
            # train-status` surfaces it so two loss curves can be told
            # apart by backward path after the fact
            self.events.emit(
                "backward", self.step,
                path=("fused_pallas" if self.fused_backward
                      else "xla_remat"),
            )
        try:
            while self.step < total_steps:
                self._check_preempt()
                report = self.train_step(batch_fn(self.step))
                if on_step is not None:
                    on_step(report)
            self._check_preempt()
            if self.is_chief:
                self._save(kind="final")
        finally:
            self.close()
        return {"lora": self.lora, "opt_state": self.opt_state,
                "rng": self.rng, "step": self.step}

    def train_step(self, batch: tuple) -> StepReport:
        """One supervised step at `self.step`: run, guard, adopt-or-skip
        (possibly roll back), checkpoint on cadence. Advances
        `self.step` by one on BOTH applied and skipped steps — a
        skipped step consumes its batch, so a run with skips equals a
        clean run minus exactly the skipped updates."""
        step = self.step
        t0 = self._clock()
        tracing = self.tracer is not None and self.tracer.enabled
        tw0 = self.tracer.now() if tracing else 0.0
        f = self._faults.fire("hang_step")
        if f is not None:
            # a wedged collective never returns; the injected stall is
            # bounded so the test process survives after the watchdog
            # hook fires
            time.sleep(float(f.get("seconds", 1.0)))
        import jax

        self.rng, _sub = jax.random.split(self.rng)
        out = self.step_fn(self.lora, self.opt_state, *batch)
        if len(out) == 4:
            new_lora, new_opt, loss, gnorm = out
        else:
            new_lora, new_opt, loss = out
            gnorm = None
        # the float() fetch blocks until the step really finished on
        # device — the watchdog beat below therefore counts completed
        # work, and the anomaly guard reads settled numbers
        loss_h = float(loss)
        gnorm_h = None if gnorm is None else float(gnorm)
        if self._wd is not None:
            self._wd.beat(step)
        loss_h, gnorm_h = self._inject_anomalies(loss_h, gnorm_h)
        reasons = self._anomaly_reasons(loss_h, gnorm_h)
        dt = self._clock() - t0
        TRAIN_STEP_SECONDS.observe(dt)
        anomaly, preempt = self._consensus(
            bool(reasons), self._preempt_flag.is_set())
        if preempt:
            # one rank's SIGTERM becomes EVERY rank's preempt flag in
            # the same per-step reduction as the anomaly verdict: all
            # ranks reach the next _check_preempt boundary together and
            # exit 43 as a group instead of one rank vanishing and
            # wedging the others' next collective until the watchdog
            self._preempt_flag.set()
        if anomaly:
            self._on_anomaly(step, loss_h, gnorm_h, reasons or
                             ("peer_anomaly",))
            report = StepReport(step, loss_h, gnorm_h, True,
                                tuple(reasons) or ("peer_anomaly",), dt)
        else:
            self.lora, self.opt_state = new_lora, new_opt
            self._consecutive_anomalies = 0
            self._applied_steps += 1
            beta = self.config.ema_beta
            self._ema = (loss_h if self._ema is None
                         else beta * self._ema + (1 - beta) * loss_h)
            self.step = step + 1
            if self.is_chief and self.step % self.config.save_every == 0:
                self._save(kind="periodic")
            report = StepReport(step, loss_h, gnorm_h, False, (), dt)
        if tracing:
            # the same span vocabulary as serving's decode_step: one
            # engine-track complete span per step, anomalies visible as
            # skipped=True plus the EventLog-mirrored "anomaly" instant
            self.tracer.complete(
                "train.step", tw0, dt, tid=0, cat="train", step=step,
                loss=report.loss, skipped=report.skipped,
            )
        if (self.config.heartbeat_every
                and self.step % self.config.heartbeat_every == 0):
            self._heartbeat(self.step)
        return report

    # ------------------------------------------------------------------
    # guards
    # ------------------------------------------------------------------

    def _inject_anomalies(self, loss_h: float, gnorm_h: Optional[float]):
        if self._faults.fire("nan_loss") is not None:
            loss_h = float("nan")
        if self._faults.fire("nan_grad") is not None:
            gnorm_h = float("nan")
        f = self._faults.fire("loss_spike")
        if f is not None:
            base = self._ema if self._ema is not None else 1.0
            loss_h = float(f.get("factor", 4.0)) * \
                self.config.spike_factor * max(abs(base), 1e-6)
        return loss_h, gnorm_h

    def _anomaly_reasons(self, loss_h: float,
                         gnorm_h: Optional[float]) -> list:
        import math

        reasons = []
        if not math.isfinite(loss_h):
            reasons.append("nan_loss")
        if gnorm_h is not None and not math.isfinite(gnorm_h):
            reasons.append("nan_grad")
        if (self._ema is not None
                and self._applied_steps >= self.config.warmup_steps
                and math.isfinite(loss_h)
                and loss_h > self.config.spike_factor * max(self._ema, 1e-12)):
            reasons.append("loss_spike")
        return reasons

    def _consensus(self, anomaly: bool, preempt: bool) -> tuple:
        from bigdl_tpu.parallel.health import consensus_any

        return tuple(consensus_any([anomaly, preempt]))

    def _on_anomaly(self, step: int, loss_h: float,
                    gnorm_h: Optional[float], reasons) -> None:
        TRAIN_ANOMALIES.inc()
        TRAIN_STEPS_SKIPPED.inc()
        self._consecutive_anomalies += 1
        self.events.emit(
            "anomaly", step, reasons=list(reasons), loss=loss_h,
            grad_norm=gnorm_h,
            consecutive=self._consecutive_anomalies,
        )
        if (self._consecutive_anomalies
                < self.config.max_consecutive_anomalies):
            # skip: discard the computed update, consume the batch
            self.step = step + 1
            return
        self._rollback(step)

    def _rollback(self, step: int) -> None:
        if self.rollbacks >= self.config.max_rollbacks:
            detail = (
                f"anomalies persist after {self.rollbacks} rollbacks "
                f"(max_rollbacks={self.config.max_rollbacks}) — data, "
                "learning rate, or hardware is bad"
            )
            self.events.emit("abort", step, abort_kind="rollback_loop",
                             detail=detail)
            raise SupervisorAbort("rollback_loop", step, detail)
        state = load_latest_train_state(
            self.ckpt_dir,
            like_lora=self._like_lora,
            like_opt_state=self._like_opt_state,
            verify=self.config.verify,
        )
        if state is None:
            detail = (
                f"no loadable checkpoint in {self.ckpt_dir} to roll "
                "back to after "
                f"{self._consecutive_anomalies} consecutive anomalies"
            )
            self.events.emit("abort", step, abort_kind="rollback_failed",
                             detail=detail)
            raise SupervisorAbort("rollback_failed", step, detail)
        self.lora = state["lora"]
        self.opt_state = state["opt_state"]
        self.rng = state["rng"]
        self.step = int(state["step"])
        self._consecutive_anomalies = 0
        self._ema = None  # re-warm: the poisoned stretch skewed it
        self._applied_steps = 0
        # counted only after a restore actually happened — the abort
        # paths above must not inflate "rollbacks performed"
        self.rollbacks += 1
        TRAIN_ROLLBACKS.inc()
        self.events.emit(
            "rollback", step, restored_step=self.step,
            path=state["path"], rollbacks=self.rollbacks,
        )

    # ------------------------------------------------------------------
    # preemption / watchdog / heartbeat
    # ------------------------------------------------------------------

    def _check_preempt(self) -> None:
        if self._faults.fire("preempt_signal") is not None:
            self._preempt_flag.set()
        if not self._preempt_flag.is_set():
            return
        path = None
        if self.is_chief:
            path = self._save(kind="emergency")
            # the metric counts checkpoints actually written: non-chief
            # ranks exiting alongside would otherwise overcount N-fold
            TRAIN_EMERGENCY_CHECKPOINTS.inc()
        self.events.emit("preempt", self.step, checkpoint=path,
                         exit_code=EXIT_PREEMPTED)
        self.close()
        self._exit(EXIT_PREEMPTED)

    def _watchdog_fired(self, idle: float) -> None:
        TRAIN_WATCHDOG_ABORTS.inc()
        self.events.emit(
            "watchdog_abort", self.step, idle_s=round(idle, 1),
            timeout_s=self.config.step_timeout_s,
            exit_code=EXIT_WATCHDOG,
        )
        if self._on_watchdog_timeout is not None:  # tests
            self._on_watchdog_timeout(idle)
            return
        self.events.close()  # the hard exit below skips atexit flushes
        print(
            f"[bigdl-tpu supervisor] no step finished for {idle:.0f}s "
            f"(> {self.config.step_timeout_s}s) at step {self.step} on "
            f"process {self.process_index} — likely a lost peer wedging "
            f"a collective; exiting {EXIT_WATCHDOG} for a restart + "
            "auto-resume from the last checkpoint.",
            file=sys.stderr, flush=True,
        )
        os._exit(EXIT_WATCHDOG)  # a blocked collective never returns

    def _heartbeat(self, step: int) -> None:
        from bigdl_tpu.parallel.health import RankDropError

        try:
            self.health.check(step)
        except RankDropError as e:
            self.events.emit(
                "rank_drop", step, missing=e.missing, present=e.present,
            )
            raise SupervisorAbort("rank_drop", step, str(e)) from e

    # ------------------------------------------------------------------

    def _save(self, kind: str) -> str:
        path = save_train_state_rotating(
            self.ckpt_dir, step=self.step,
            keep_last=self.config.keep_last,
            lora=self.lora, opt_state=self.opt_state, rng=self.rng,
        )
        self.events.emit("checkpoint", self.step, ckpt_kind=kind,
                         path=path)
        return path


class _NullTrainInjector(TrainFaultInjector):
    """Module-shared inert default (mirrors faults.NULL_INJECTOR)."""

    def arm(self, *a, **k):  # pragma: no cover - guard rail
        raise RuntimeError(
            "this is the shared no-op injector; construct your own "
            "TrainFaultInjector and pass it via faults="
        )

    def fire(self, point: str) -> Optional[dict]:
        return None


_NULL_TRAIN_INJECTOR = _NullTrainInjector()
