"""QLoRA: LoRA adapters over a frozen low-bit base.

Reference: `transformers/qlora.py` (`LoraLowBitLinear`:66-144 — frozen
LowBitLinear base + bf16 LoRA branch; autograd through the quantized
matmul via `MatMulLowBit.backward`, low_bit_linear.py:500-541).

TPU design: the base weights are QTensor leaves that are simply not
differentiated — `jax.grad` w.r.t. the LoRA tree alone gives exactly the
reference's backward (dequantized W^T participates in the VJP as a
constant; XLA rematerializes the dequant, no custom autograd class
needed). One jitted train step covers forward, backward, and the optax
update, sharded over the same (dp, sp, tp) mesh as inference.

The frozen-base matmul runs fused in BOTH directions (ops/linear.py
routes training shapes — rows > `_GEMV_MAX_ROWS` — to the Pallas kernel
under a custom_vjp): the forward's y = x @ dq(W)^T and the backward's
dx = g @ dq(W) both dequantize base-weight tiles in VMEM
(ops/pallas/qmatmul.py forward, ops/pallas/qbackward.py dx) instead of
materializing a bf16 copy of W in HBM per step. The old XLA
rematerialized-dequant backward survives as the parity oracle behind
`make_train_step(..., fused_backward=False)` /
`ops.linear.fused_backward_scope(False)` (parity:
tests/test_qbackward.py; arxiv 2306.11987).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax

from bigdl_tpu.models.config import ModelConfig

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def _target_dims(config: ModelConfig, name: str) -> tuple[int, int]:
    H, I = config.hidden_size, config.intermediate_size
    return {
        "wq": (config.q_dim, H),
        "wk": (config.kv_dim, H),
        "wv": (config.kv_dim, H),
        "wo": (H, config.q_dim),
        "w_gate": (I, H),
        "w_up": (I, H),
        "w_down": (H, I),
    }[name]


def init_lora(
    config: ModelConfig,
    key: jax.Array,
    rank: int = 8,
    alpha: float = 16.0,
    targets: tuple[str, ...] = DEFAULT_TARGETS,
    dtype=jnp.bfloat16,
) -> dict:
    """LoRA tree: {'layers': {target: {'a': [L,r,in], 'b': [L,out,r]}},
    'scale': alpha/rank}. A ~ N(0, 1/r), B = 0 (standard init: adapter
    starts as identity)."""
    L = config.num_hidden_layers
    layers = {}
    for t in targets:
        out_dim, in_dim = _target_dims(config, t)
        key, k = jax.random.split(key)
        layers[t] = {
            "a": (jax.random.normal(k, (L, rank, in_dim), jnp.float32) / rank).astype(dtype),
            "b": jnp.zeros((L, out_dim, rank), dtype),
        }
    return {"layers": layers, "scale": jnp.asarray(alpha / rank, dtype)}


# lora target -> (merged base name, row-slice index) for the fused layout
# (models/llama.merge_fused_params)
_MERGED_HOME = {
    "wq": ("wqkv", 0), "wk": ("wqkv", 1), "wv": ("wqkv", 2),
    "w_gate": ("w_gateup", 0), "w_up": ("w_gateup", 1),
}


def merge_lora(params: dict, lora: dict, requantize: Optional[str] = None) -> dict:
    """Fold adapters into the base (ReLoRA's merge step, relora.py:64-150).

    Dense bases merge exactly; quantized bases are dequantized, merged,
    and re-quantized to `requantize` (defaults to their own qtype).
    Handles both the split layout and the fused one (merge_fused_params):
    deltas land in each target's row slice of the fused base, located
    from the lora pairs' own output widths, and every base is requantized
    at most once (deltas into the same fused weight are accumulated
    first, so quantization noise doesn't compound per target).
    """
    from bigdl_tpu.quant import QTensor, quantize

    out_layers = dict(params["layers"])
    scale = jnp.asarray(lora["scale"], jnp.float32)

    # row offsets inside fused bases derive from the target's OWN lora B
    # width plus the fused base's total rows — never from peer targets
    # (a lora trained on wk/wv alone must still land in the k/v rows)
    widths = {t: p["b"].shape[-2] for t, p in lora["layers"].items()}

    def base_rows(name: str) -> int:
        # QTensor.shape is the LOGICAL shape for every storage (for
        # packed_u8/packed_planes, data.shape[-1] is bytes, not elements)
        return params["layers"][name].shape[-2]

    def row_start(target: str) -> int:
        name, idx = _MERGED_HOME[target]
        total = base_rows(name)
        if name == "wqkv":
            kd = widths[target] if target in ("wk", "wv") else None
            if target == "wq":
                return 0
            # total = QD + 2*KD with KD = this target's own width
            return total - 2 * kd if target == "wk" else total - kd
        # w_gateup: gate rows first, both halves share width I
        return 0 if target == "w_gate" else total // 2

    # base name -> list of (row_offset|None, delta)
    pending: dict[str, list] = {}
    for t, pair in lora["layers"].items():
        delta = (
            jnp.einsum("lor,lri->loi", pair["b"].astype(jnp.float32),
                       pair["a"].astype(jnp.float32)) * scale
        )
        if t in params["layers"]:
            pending.setdefault(t, []).append((None, delta))
        elif t in _MERGED_HOME and _MERGED_HOME[t][0] in params["layers"]:
            pending.setdefault(_MERGED_HOME[t][0], []).append(
                (row_start(t), delta)
            )
        else:
            raise KeyError(
                f"lora target {t!r} not found in params (neither split nor "
                f"fused layout)"
            )

    for name, deltas in pending.items():
        base = params["layers"][name]
        quantized = isinstance(base, QTensor)
        dense = base.dequantize(jnp.float32) if quantized else base.astype(jnp.float32)
        for off, delta in deltas:
            if off is None:
                dense = dense + delta
            else:
                dense = dense.at[..., off:off + delta.shape[-2], :].add(delta)
        out_layers[name] = (
            quantize(dense, requantize or base.qtype) if quantized
            else dense.astype(base.dtype)
        )
    out = dict(params)
    out["layers"] = out_layers
    return out


def next_token_loss(
    config: ModelConfig,
    forward_fn: Callable,
    params: dict,
    lora: Optional[dict],
    tokens: jax.Array,  # [B, T]
    loss_mask: jax.Array,  # [B, T] 1.0 where the *target* token counts
) -> jax.Array:
    """Causal LM cross-entropy: predict tokens[:, 1:] from tokens[:, :-1]."""
    logits, _ = forward_fn(config, params, tokens[:, :-1], None, lora=lora)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def make_train_step(
    config: ModelConfig,
    forward_fn: Callable,
    optimizer: optax.GradientTransformation,
    seq_spec=None,
    ring_mesh=None,
    ring_axis: str = "sp",
    batch_axis: str = "dp",
    remat: bool = False,
    return_grad_norm: bool = False,
    fused_backward: bool = True,
):
    """Returns jittable step(params, lora, opt_state, tokens, loss_mask) ->
    (lora, opt_state, loss). Only lora['layers'] is trained (the alpha/rank
    scale stays fixed); init opt_state with optimizer.init(lora['layers']).
    Donate lora/opt_state at the jit call site — UNLESS the step runs
    under the training supervisor, whose anomaly-skip path must keep
    the previous buffers alive for one step (train/supervisor.py).

    return_grad_norm=True appends optax.global_norm(grads) to the
    outputs — the supervisor's overflow guard (quantized-grad NaN/inf
    shows up in the norm a step before it reaches the loss; arxiv
    2306.11987) — at the cost of one extra reduction per step.

    seq_spec: optional PartitionSpec (e.g. P('dp', 'sp')) constraining the
    input token grid — sequence-parallel training: embedding/norm/MLP run
    on sequence shards; without ring_mesh XLA all-gathers KV around
    attention.

    remat=True checkpoints each decoder layer (jax.checkpoint around the
    scan body): the backward recomputes the layer instead of saving its
    activations — with the flash-train kernel this makes per-layer saved
    state O(B*T*H) instead of O(B*T*(3H+2I)), the long-context lever.

    ring_mesh: pass the Mesh to replace those all-gathers with ring
    attention (parallel/ring.py) — each device keeps 1/sp of the KV and
    shards rotate over ICI, making attention memory O(T/sp) for
    long-context training. Requires an enclosing mesh context (parallel._compat.set_mesh) and
    sliding_window/softcap-free attention (llama-family default).

    fused_backward=False traces the step with the XLA
    rematerialized-dequant dx instead of the Pallas fused backward
    (ops/pallas/qbackward.py) — the parity oracle for A/B-ing loss
    curves across the flip. The choice is baked into the jaxpr at trace
    time (ops.linear.fused_backward_scope), so it is per-step-function,
    not per-call; the supervisor EventLog records which path a run used.
    """
    attention_override = None
    if ring_mesh is not None:
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.parallel._compat import shard_map as _shard_map
        from bigdl_tpu.parallel.ring import ring_attention

        # features the ring path does not implement — fail loudly instead
        # of silently optimizing a different loss than the dense path
        assert config.attn_logit_softcap is None, "ring: no logit softcap"
        assert config.sliding_window is None, "ring: no sliding window"
        assert not config.alibi, "ring: no alibi"

        n = ring_mesh.shape[ring_axis]
        # shard heads over tp too (when present and divisible): each tp
        # device keeps its own head shard instead of all-gathering q/k/v
        head_axis = None
        if "tp" in ring_mesh.shape and ring_mesh.shape["tp"] > 1:
            tp = ring_mesh.shape["tp"]
            if (config.num_attention_heads % tp == 0
                    and config.num_key_value_heads % tp == 0):
                head_axis = "tp"
        qspec = P(batch_axis, ring_axis, head_axis, None)

        def _local(q, k, v, start):
            return ring_attention(
                q, k, v, axis_name=ring_axis, axis_size=n, causal=True,
                scale=config.attn_scale, start=start,
            )

        attention_override = _shard_map(
            _local,
            mesh=ring_mesh,
            in_specs=(qspec, qspec, qspec, P(batch_axis)),
            out_specs=qspec,
            check_vma=False,
        )

    inner_forward = forward_fn
    if seq_spec is not None or attention_override is not None or remat:
        def inner_forward(cfg, params, toks, cache, lora=None):
            if seq_spec is not None:
                toks = jax.lax.with_sharding_constraint(toks, seq_spec)
            kw = {"remat": True} if remat else {}
            return forward_fn(
                cfg, params, toks, cache, lora=lora,
                attention_override=attention_override, **kw,
            )

    def step(params, lora, opt_state, tokens, loss_mask):
        from bigdl_tpu.ops.linear import fused_backward_scope

        scale = lora["scale"]
        # the scope is read at TRACE time inside the custom_vjp bwd
        # rules, so wrapping the value_and_grad call (which runs during
        # jit tracing of `step`) bakes the chosen dx path into the jaxpr
        with fused_backward_scope(fused_backward):
            loss, grads = jax.value_and_grad(
                lambda layers: next_token_loss(
                    config, inner_forward, params,
                    {"layers": layers, "scale": scale}, tokens, loss_mask,
                )
            )(lora["layers"])
        updates, opt_state = optimizer.update(grads, opt_state, lora["layers"])
        layers = optax.apply_updates(lora["layers"], updates)
        new_lora = {"layers": layers, "scale": scale}
        if return_grad_norm:
            return new_lora, opt_state, loss, optax.global_norm(grads)
        return new_lora, opt_state, loss

    return step
