"""GaLore — Gradient Low-Rank Projection as an optax transform.

Counterpart of the reference's GaLore finetuning recipe
(/root/reference/python/llm/example/GPU/LLM-Finetuning/GaLore/, which
drives the galore-torch AdamW8bit optimizer): full-parameter training at
LoRA-like optimizer memory by running the inner optimizer in a low-rank
subspace of the gradient. Per 2-D weight G [m, n]:

    P   <- top-r singular vectors of G (recomputed every
           `update_proj_gap` steps; projects the SMALLER side)
    low <- project(G, P)              # [r, n] or [m, r]
    upd <- inner.update(low)          # Adam moments live at rank r
    dW  <- scale * back_project(upd)

TPU-native formulation: the projector refresh is a `lax.cond`-guarded
`jnp.linalg.svd` inside the jitted update (no host sync, works under
pjit — XLA computes the SVD on device), and the whole thing composes as
a standard `optax.GradientTransformation`, so it drops into the existing
full-FT train step (train/recipes.py make_full_train_step).

Non-2-D leaves (norms, biases, stacked-scan 3-D weights below the rank
threshold... anything is_projected rejects) pass through the inner
optimizer unprojected, matching galore-torch's param-group split.

The inner transform must not require the parameter values (the moments
live at projected shapes, where no real params exist): use
`optax.adam` / `optax.scale_by_adam`, and compose weight decay OUTSIDE
the projection — where galore-torch also applies it:

    optax.chain(galore(optax.scale_by_adam(), rank=128),
                optax.add_decayed_weights(1e-2), optax.scale(-lr))
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax


class GaLoreState(NamedTuple):
    step: jax.Array  # scalar int32
    proj: dict  # per-leaf projector (None for pass-through leaves)
    inner: optax.OptState  # inner optimizer state over projected shapes


def _is_projected(p, rank: int) -> bool:
    # stacked-scan layers are [L, O, I]: project per layer over (O, I)
    return p.ndim in (2, 3) and min(p.shape[-2:]) > rank


def _orient_left(p) -> bool:
    # project the smaller side: left (rows) when m <= n
    return p.shape[-2] <= p.shape[-1]


def _project(g, P, left: bool):
    if left:  # P [..., m, r]
        return jnp.einsum("...mr,...mn->...rn", P, g)
    return jnp.einsum("...mn,...nr->...mr", g, P)  # P [..., n, r]


def _back(low, P, left: bool):
    if left:
        return jnp.einsum("...mr,...rn->...mn", P, low)
    return jnp.einsum("...mr,...nr->...mn", low, P)


def _svd_projector(g, rank: int, left: bool):
    gf = g.astype(jnp.float32)
    if not left:
        gf = jnp.swapaxes(gf, -1, -2)  # svd of g^T: U spans the n side
    u, _, _ = jnp.linalg.svd(gf, full_matrices=False)
    return u[..., :rank]


def galore(
    inner: optax.GradientTransformation,
    rank: int = 128,
    update_proj_gap: int = 200,
    scale: float = 0.25,
) -> optax.GradientTransformation:
    """Wrap `inner` (e.g. optax.adam / optax.scale_by_adam — NOT adamw;
    see the module docstring for weight-decay composition) with GaLore
    projection."""

    def proj_shape(p):
        if not _is_projected(p, rank):
            return p
        if _orient_left(p):
            return jnp.zeros((*p.shape[:-2], rank, p.shape[-1]), p.dtype)
        return jnp.zeros((*p.shape[:-2], p.shape[-2], rank), p.dtype)

    def init(params):
        # pass-through leaves get a zero-size placeholder (None would be
        # an empty pytree node and break multi-tree maps)
        proj = jax.tree.map(
            lambda p: (
                jnp.zeros(
                    (*p.shape[:-2], p.shape[-2] if _orient_left(p)
                     else p.shape[-1], rank),
                    jnp.float32,
                )
                if _is_projected(p, rank) else jnp.zeros((0,), jnp.float32)
            ),
            params,
        )
        virtual = jax.tree.map(proj_shape, params)
        return GaLoreState(
            step=jnp.zeros((), jnp.int32), proj=proj,
            inner=inner.init(virtual),
        )

    def update(grads, state, params=None):
        refresh = state.step % update_proj_gap == 0

        def upd_proj(g, P):
            if P.size == 0:
                return P
            left = _orient_left(g)
            return jax.lax.cond(
                refresh,
                lambda: _svd_projector(g, rank, left),
                lambda: P,
            )

        proj = jax.tree.map(upd_proj, grads, state.proj)

        def low_g(g, P):
            if P.size == 0:
                return g
            return _project(g.astype(jnp.float32), P, _orient_left(g)).astype(g.dtype)

        low = jax.tree.map(low_g, grads, proj)
        # params=None: moments live at projected shapes (see module doc)
        low_upd, inner_state = inner.update(low, state.inner)

        def full_upd(u, P, g):
            if P.size == 0:
                return u
            return (
                scale * _back(u.astype(jnp.float32), P, _orient_left(g))
            ).astype(u.dtype)

        updates = jax.tree.map(full_upd, low_upd, proj, grads)
        return updates, GaLoreState(
            step=state.step + 1, proj=proj, inner=inner_state
        )

    return optax.GradientTransformation(init, update)
