"""Atomic train-state checkpoint/resume for the jitted finetuning loops.

The reference leans on HF Trainer/PEFT checkpointing (SURVEY.md §5;
relora.py:64-150 merges adapters into saved checkpoints); our training
loops are jitted steps with explicit state, so the checkpoint is the
state itself: (lora tree, optax optimizer state, step counter, PRNG key,
optionally the merged base params for mid-ReLoRA resume — the base
mutates at every merge-and-reset, so a ReLoRA resume without it would
continue from the wrong weights).

Format: ONE .npz file (flattened pytree leaves as bit-views via
convert/low_bit's codec, plus the JSON metadata as a zero-dim array),
written to a temp name and os.replace()d into place — a kill at any
instant leaves either the old or the new checkpoint, never a torn or
missing one, for both first saves and overwrites.

Pytree structure is NOT serialized: load takes "like" templates (the
freshly-initialized lora/opt_state the caller already has) and unflattens
onto their treedef, verifying leaf shapes and dtypes — the standard JAX
restore pattern, which keeps optax's nested NamedTuples out of the file
format.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.convert.low_bit import _decode as _decode_bits
from bigdl_tpu.convert.low_bit import _encode as _encode_bits


def _encode(arr) -> tuple[np.ndarray, str]:
    if jnp.issubdtype(jnp.asarray(arr).dtype, jax.dtypes.prng_key):
        return np.asarray(jax.random.key_data(arr)), "prng_key"
    return _encode_bits(arr)


def _decode(a: np.ndarray, dtype_name: str):
    if dtype_name == "prng_key":
        return jax.random.wrap_key_data(jnp.asarray(a))
    return _decode_bits(a, dtype_name)


def save_train_state(
    path: str,
    *,
    lora: dict,
    opt_state: Any,
    step: int,
    rng: jax.Array,
    params: Optional[dict] = None,
    resets: int = 0,
) -> None:
    """Atomically write the full training state to `path` (one file).
    Pass `params` when the base mutates (ReLoRA merges); plain QLoRA's
    frozen base reloads from its own checkpoint and needs only the
    adapter state here."""
    state = {"lora": lora, "opt_state": opt_state, "rng": rng}
    if params is not None:
        state["params"] = params
    leaves = jax.tree.leaves(state)

    arrays, dtypes = {}, []
    for i, leaf in enumerate(leaves):
        a, dt = _encode(leaf)
        arrays[f"leaf_{i:05d}"] = a
        dtypes.append(dt)
    arrays["meta"] = np.asarray(json.dumps({
        "format_version": 2,
        "step": int(step),
        "resets": int(resets),
        "n_leaves": len(leaves),
        "dtypes": dtypes,
        "has_params": params is not None,
    }))

    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_train_state(
    path: str,
    *,
    like_lora: dict,
    like_opt_state: Any,
    like_params: Optional[dict] = None,
) -> dict:
    """Returns {lora, opt_state, rng, step, resets[, params]}; the
    `like_*` templates (e.g. a freshly-initialized lora + optimizer.init)
    provide the pytree structure to unflatten onto."""
    npz = np.load(path, allow_pickle=False)
    meta = json.loads(str(npz["meta"]))
    if meta["format_version"] != 2:
        raise ValueError(f"unsupported format_version {meta['format_version']}")
    like = {
        "lora": like_lora, "opt_state": like_opt_state,
        "rng": jax.random.PRNGKey(0),
    }
    if meta["has_params"]:
        if like_params is None:
            raise ValueError(
                "checkpoint carries base params (ReLoRA); pass like_params"
            )
        like["params"] = like_params
    treedef = jax.tree.structure(like)
    like_leaves = jax.tree.leaves(like)
    if treedef.num_leaves != meta["n_leaves"]:
        raise ValueError(
            f"checkpoint has {meta['n_leaves']} leaves but the templates "
            f"have {treedef.num_leaves} — optimizer or lora config changed"
        )

    leaves = []
    for i, (dt, ref) in enumerate(zip(meta["dtypes"], like_leaves)):
        leaf = _decode(npz[f"leaf_{i:05d}"], dt)
        # typed-vs-raw PRNG keys have different logical shapes; the rng
        # leaf's template is a placeholder, so skip its checks
        if dt != "prng_key" and hasattr(ref, "shape"):
            if tuple(leaf.shape) != tuple(ref.shape):
                raise ValueError(
                    f"leaf {i}: checkpoint shape {tuple(leaf.shape)} != "
                    f"template {tuple(ref.shape)}"
                )
            if jnp.asarray(ref).dtype != leaf.dtype:
                raise ValueError(
                    f"leaf {i}: checkpoint dtype {leaf.dtype} != "
                    f"template {jnp.asarray(ref).dtype} — a resumed run "
                    "would not bit-reproduce the original curve"
                )
        leaves.append(leaf)
    state = jax.tree.unflatten(treedef, leaves)
    state["step"] = meta["step"]
    state["resets"] = meta["resets"]
    return state
