"""Atomic train-state checkpoint/resume for the jitted finetuning loops.

The reference leans on HF Trainer/PEFT checkpointing (SURVEY.md §5;
relora.py:64-150 merges adapters into saved checkpoints); our training
loops are jitted steps with explicit state, so the checkpoint is the
state itself: (lora tree, optax optimizer state, step counter, PRNG key,
optionally the merged base params for mid-ReLoRA resume — the base
mutates at every merge-and-reset, so a ReLoRA resume without it would
continue from the wrong weights).

Format: ONE .npz file (flattened pytree leaves as bit-views via
convert/low_bit's codec, plus the JSON metadata as a zero-dim array),
written to a temp name and os.replace()d into place — a kill at any
instant leaves either the old or the new checkpoint, never a torn or
missing one, for both first saves and overwrites.

Pytree structure is NOT serialized: load takes "like" templates (the
freshly-initialized lora/opt_state the caller already has) and unflattens
onto their treedef, verifying leaf shapes and dtypes — the standard JAX
restore pattern, which keeps optax's nested NamedTuples out of the file
format.

Durability (utils/durability.py): the meta record carries per-leaf
crc32/sha256 digests checked by `load_train_state(verify=...)`, the
write goes through the shared atomic tmp+fsync+rename protocol (with an
optional disk-fault injector for tests), and `save_train_state_rotating`
/ `load_latest_train_state` implement keep-last-k retention where resume
scans candidates newest-first and *skips* corrupt checkpoints with a
warning — one rotted file costs one save interval, not the run.
"""

from __future__ import annotations

import json
import os
import re
import warnings
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.convert.low_bit import _decode as _decode_bits
from bigdl_tpu.convert.low_bit import _encode as _encode_bits
from bigdl_tpu.utils import durability
from bigdl_tpu.utils.durability import IntegrityError


def _encode(arr) -> tuple[np.ndarray, str]:
    if jnp.issubdtype(jnp.asarray(arr).dtype, jax.dtypes.prng_key):
        return np.asarray(jax.random.key_data(arr)), "prng_key"
    return _encode_bits(arr)


def _decode(a: np.ndarray, dtype_name: str):
    if dtype_name == "prng_key":
        return jax.random.wrap_key_data(jnp.asarray(a))
    return _decode_bits(a, dtype_name)


def save_train_state(
    path: str,
    *,
    lora: dict,
    opt_state: Any,
    step: int,
    rng: jax.Array,
    params: Optional[dict] = None,
    resets: int = 0,
    faults=None,
) -> None:
    """Atomically write the full training state to `path` (one file).
    Pass `params` when the base mutates (ReLoRA merges); plain QLoRA's
    frozen base reloads from its own checkpoint and needs only the
    adapter state here. `faults` threads a DiskFaultInjector through the
    atomic write (tests only)."""
    state = {"lora": lora, "opt_state": opt_state, "rng": rng}
    if params is not None:
        state["params"] = params
    leaves = jax.tree.leaves(state)

    arrays, dtypes = {}, []
    for i, leaf in enumerate(leaves):
        a, dt = _encode(leaf)
        arrays[f"leaf_{i:05d}"] = a
        dtypes.append(dt)

    def write(f) -> None:
        # one serialization pass: each leaf is encoded to .npy bytes
        # once, digested, and written (durability.write_npz); the meta
        # member — carrying those digests — lands in the same zip last
        # (it cannot self-digest; the zip member crc32 still covers it)
        import zipfile

        with zipfile.ZipFile(f, "w", zipfile.ZIP_STORED) as zf:
            tensors = {}
            for k in sorted(arrays):
                tensors[k] = durability.add_npz_member(zf, k, arrays[k])
            meta = {
                "format_version": 2,
                "step": int(step),
                "resets": int(resets),
                "n_leaves": len(leaves),
                "dtypes": dtypes,
                "has_params": params is not None,
                "integrity": durability.integrity_section(tensors),
            }
            durability.add_npz_member(zf, "meta",
                                      np.asarray(json.dumps(meta)))

    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    durability.atomic_write(path, write, faults=faults)


def _verify_leaves(path: str, meta: dict, verify: str) -> dict:
    """Read + verify every leaf member (durability.verify_npz_members).
    Returns {name: np.ndarray} of clean leaves; raises IntegrityError
    naming each corrupt/missing one. Structural checks (missing members,
    unreadable members — the zip layer's own member crc fires on read)
    apply in every mode; digest comparison is mode-gated; `full` adds a
    non-finite scan of float leaves."""
    n_leaves = meta.get("n_leaves")
    dtypes = meta.get("dtypes")
    if not isinstance(n_leaves, int) or not isinstance(dtypes, list):
        # parseable meta JSON with rotted keys is corruption, not a
        # KeyError — the rotation scan must be able to skip past it
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, detail="damaged meta record (n_leaves/dtypes missing)",
        )
    names = [f"leaf_{i:05d}" for i in range(n_leaves)]
    integrity = (meta.get("integrity") or {}).get("tensors")
    arrays, corrupted, missing, extra = durability.verify_npz_members(
        path, integrity, verify, names, ignore={"meta"},
    )
    if verify == "full":
        for n, dt in zip(names, dtypes):
            if n not in arrays:
                continue
            detail = durability.scan_non_finite(arrays[n], dt)
            if detail is not None:
                corrupted[n] = f"non_finite: {detail}"
                arrays.pop(n)
    if corrupted or missing or extra:
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(path, corrupted=corrupted, missing=missing,
                             extra=extra)
    return arrays


def load_train_state(
    path: str,
    *,
    like_lora: dict,
    like_opt_state: Any,
    like_params: Optional[dict] = None,
    verify: str = "fast",
) -> dict:
    """Returns {lora, opt_state, rng, step, resets[, params]}; the
    `like_*` templates (e.g. a freshly-initialized lora + optimizer.init)
    provide the pytree structure to unflatten onto.

    verify: "off" | "fast" (crc32, default) | "full" (sha256 + non-finite
    scan of float leaves). Digest mismatches, unreadable members, and
    missing leaves raise a structured IntegrityError naming each bad
    leaf; an unreadable file raises IntegrityError too (FileNotFoundError
    stays FileNotFoundError) — so the rotation scan can distinguish
    corruption (skip, warn) from config drift (raise)."""
    durability.check_verify_mode(verify)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        npz = np.load(path, allow_pickle=False)
        meta = json.loads(str(npz["meta"]))
    except Exception as e:
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, detail=f"unreadable checkpoint: {type(e).__name__}: {e}",
        ) from e
    missing_keys = [k for k in ("format_version", "step", "resets",
                                "has_params") if k not in meta]
    if missing_keys:
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, detail="damaged meta record (missing keys: "
                         f"{', '.join(missing_keys)})",
        )
    if meta["format_version"] != 2:
        # there is exactly one train-ckpt format version, so any other
        # value in a parsed meta is bit rot in that field, not a legacy
        # file — raise the SKIPPABLE (and counted) IntegrityError so one
        # rotted meta costs the rotation scan one candidate, not the
        # whole resume
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, detail=f"unsupported format_version "
                         f"{meta['format_version']!r} (rotted meta?)",
        )
    arrays = _verify_leaves(path, meta, verify)
    like = {
        "lora": like_lora, "opt_state": like_opt_state,
        "rng": jax.random.PRNGKey(0),
    }
    if meta["has_params"]:
        if like_params is None:
            raise ValueError(
                "checkpoint carries base params (ReLoRA); pass like_params"
            )
        like["params"] = like_params
    treedef = jax.tree.structure(like)
    like_leaves = jax.tree.leaves(like)
    if treedef.num_leaves != meta["n_leaves"]:
        raise ValueError(
            f"checkpoint has {meta['n_leaves']} leaves but the templates "
            f"have {treedef.num_leaves} — optimizer or lora config changed"
        )

    leaves = []
    for i, (dt, ref) in enumerate(zip(meta["dtypes"], like_leaves)):
        leaf = _decode(arrays[f"leaf_{i:05d}"], dt)
        # typed-vs-raw PRNG keys have different logical shapes; the rng
        # leaf's template is a placeholder, so skip its checks
        if dt != "prng_key" and hasattr(ref, "shape"):
            if tuple(leaf.shape) != tuple(ref.shape):
                raise ValueError(
                    f"leaf {i}: checkpoint shape {tuple(leaf.shape)} != "
                    f"template {tuple(ref.shape)}"
                )
            if jnp.asarray(ref).dtype != leaf.dtype:
                raise ValueError(
                    f"leaf {i}: checkpoint dtype {leaf.dtype} != "
                    f"template {jnp.asarray(ref).dtype} — a resumed run "
                    "would not bit-reproduce the original curve"
                )
        leaves.append(leaf)
    state = jax.tree.unflatten(treedef, leaves)
    state["step"] = meta["step"]
    state["resets"] = meta["resets"]
    return state


# ---------------------------------------------------------------------------
# rotation: keep-last-k retention + corrupt-skipping resume
# ---------------------------------------------------------------------------

_CKPT_RE = re.compile(r"^ckpt-(\d{8})\.npz$")
# stale tmps of crashed rotating saves, swept by the rotation prune
_CKPT_TMP_RE = re.compile(r"^ckpt-\d{8}\.npz\.tmp-\d+$")


def list_train_checkpoints(ckpt_dir: str) -> list:
    """Rotated checkpoint paths in `ckpt_dir`, NEWEST (highest step)
    first."""
    if not os.path.isdir(ckpt_dir):
        return []
    found = []
    for name in os.listdir(ckpt_dir):
        m = _CKPT_RE.match(name)
        if m:
            found.append((int(m.group(1)), os.path.join(ckpt_dir, name)))
    return [p for _, p in sorted(found, reverse=True)]


def save_train_state_rotating(
    ckpt_dir: str, *, step: int, keep_last: int = 3, faults=None, **state,
) -> str:
    """Save `ckpt-<step:08d>.npz` into `ckpt_dir` (atomic, digested),
    then prune everything beyond the newest `keep_last` checkpoints.
    Prune runs AFTER the new save commits — a kill anywhere leaves at
    least the previous `keep_last` generation intact. Returns the new
    checkpoint path."""
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    if step < 0 or step > 10 ** 8 - 1:
        raise ValueError(f"step {step} outside the 8-digit rotation range")
    path = os.path.join(ckpt_dir, f"ckpt-{step:08d}.npz")
    save_train_state(path, step=step, faults=faults, **state)
    # prune beyond keep_last, PLUS stale tmps from earlier killed saves:
    # atomic_write's sweep only covers its own target path, and rotation
    # uses a new filename every step, so a crashed step's tmp would
    # otherwise persist forever
    stale = [
        os.path.join(ckpt_dir, n) for n in os.listdir(ckpt_dir)
        if _CKPT_TMP_RE.match(n)
    ]
    for old in list_train_checkpoints(ckpt_dir)[keep_last:] + stale:
        try:
            os.unlink(old)
        except OSError:  # pragma: no cover - racing cleanup is fine
            pass
    return path


def load_latest_train_state(
    ckpt_dir: str,
    *,
    like_lora: dict,
    like_opt_state: Any,
    like_params: Optional[dict] = None,
    verify: str = "fast",
) -> Optional[dict]:
    """Resume from the newest loadable rotated checkpoint: candidates
    are scanned newest-first and corrupt ones (IntegrityError — rot,
    torn files, digest mismatches) are SKIPPED with a warning instead of
    killing the resume; template/config mismatches still raise (an older
    checkpoint would mismatch identically — skipping would hide a real
    bug). Returns the loaded state dict with its source under
    state["path"], or None when no loadable checkpoint exists."""
    for path in list_train_checkpoints(ckpt_dir):
        try:
            state = load_train_state(
                path, like_lora=like_lora, like_opt_state=like_opt_state,
                like_params=like_params, verify=verify,
            )
        except (IntegrityError, FileNotFoundError) as e:
            # every IntegrityError raise site already bumped
            # durability.VERIFY_FAILURES (so skipped-at-resume corruption
            # shows on bigdl_tpu_checkpoint_verify_failures_total exactly
            # like a direct verify= load; regression-tested in
            # tests/test_train_supervisor.py). FileNotFoundError is a
            # prune race, not corruption — not counted.
            warnings.warn(
                f"skipping corrupt train checkpoint {path}: {e}"
            )
            continue
        state["path"] = path
        return state
    return None


def inspect_train_checkpoint(path: str) -> dict:
    """Template-free fast-mode inspection for `bigdl-tpu train-status`:
    {path, step, ok, detail, n_leaves, size, mtime}. Unlike
    `load_train_state` this needs no like_* trees (nothing is decoded
    into a pytree) — it answers "would the rotation scan accept this
    candidate?" cheaply. Verification failures are reported in-band
    (ok=False + detail), and still bump the process-wide counter via
    the shared verify path."""
    out = {
        "path": path, "step": None, "ok": False, "detail": "",
        "n_leaves": None, "size": None, "mtime": None,
    }
    try:
        st = os.stat(path)
        out["size"], out["mtime"] = st.st_size, st.st_mtime
    except OSError as e:
        out["detail"] = f"{type(e).__name__}: {e}"
        return out
    try:
        npz = np.load(path, allow_pickle=False)
        meta = json.loads(str(npz["meta"]))
    except Exception as e:
        durability.VERIFY_FAILURES.inc()
        out["detail"] = f"unreadable checkpoint: {type(e).__name__}: {e}"
        return out
    out["step"] = meta.get("step")
    out["n_leaves"] = meta.get("n_leaves")
    if meta.get("format_version") != 2:
        durability.VERIFY_FAILURES.inc()
        out["detail"] = (f"unsupported format_version "
                         f"{meta.get('format_version')!r}")
        return out
    try:
        _verify_leaves(path, meta, "fast")
    except IntegrityError as e:
        out["detail"] = str(e)
        return out
    out["ok"] = True
    return out


def inspect_train_checkpoints_dir(ckpt_dir: str) -> list:
    """Inspection rows for every rotated candidate, newest first (the
    order the resume scan tries them)."""
    return [inspect_train_checkpoint(p)
            for p in list_train_checkpoints(ckpt_dir)]


def verify_train_checkpoint(path: str) -> "durability.VerifyReport":
    """Full-mode per-leaf verification for the `bigdl-tpu verify` CLI;
    findings land in the report rows instead of raising."""
    try:
        npz = np.load(path, allow_pickle=False)
        meta = json.loads(str(npz["meta"]))
    except Exception as e:
        return durability.VerifyReport(
            path, "train", rows=[],
            detail=f"unreadable checkpoint: {type(e).__name__}: {e}",
        )
    try:
        arrays = _verify_leaves(path, meta, "full")
    except IntegrityError as e:
        rows = durability.rows_from_error(e)
        bad = e.bad_tensors
        n_leaves = meta.get("n_leaves")
        rows += [
            durability.TensorReport(f"leaf_{i:05d}", "ok")
            for i in range(n_leaves if isinstance(n_leaves, int) else 0)
            if f"leaf_{i:05d}" not in bad
        ]
        return durability.VerifyReport(path, "train", rows=rows,
                                       detail=e.detail)
    return durability.VerifyReport(path, "train", rows=[
        durability.TensorReport(n, "ok") for n in sorted(arrays)
    ])
