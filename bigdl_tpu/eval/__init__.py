"""Evaluation harness (reference: dev/benchmark/{perplexity,harness} in
/root/reference)."""

from bigdl_tpu.eval.perplexity import perplexity

__all__ = ["perplexity"]
