"""Perplexity evaluation.

Equivalent of the reference's wikitext runner
(`dev/benchmark/perplexity/run_wikitext.py` in /root/reference, which
backs the README quality table §6 of SURVEY.md): strided sliding-window
NLL over a token stream, jitted per window shape. The quality gate for
every quantization format — sym_int4 must land within the README table's
delta of fp16.
"""

from __future__ import annotations

import functools
from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models.config import ModelConfig


@functools.partial(jax.jit, static_argnames=("config", "forward"))
def _window_nll(config: ModelConfig, forward, params, tokens, valid, start):
    """tokens [1, T]; valid [T-1] marks target positions scored in this
    window (stride overlap is context only); start [1] left-pad offset so
    pad tokens are masked out of attention and consume no rope positions
    (the HF/reference strided protocol). Returns (sum_nll, n)."""
    logits, _ = forward(config, params, tokens[:, :-1], None, start=start)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None].astype(jnp.int32), -1)[0, :, 0]
    v = valid.astype(jnp.float32)
    return jnp.sum(nll * v), jnp.sum(v)


def perplexity(
    model,
    token_stream: Iterable[int],
    window: int = 512,
    stride: Optional[int] = None,
    max_tokens: Optional[int] = None,
    return_count: bool = False,
):
    """model: TpuModel. token_stream: the corpus as one token sequence
    (e.g. tokenizer("\\n\\n".join(wikitext))['input_ids']).

    stride defaults to window (disjoint windows); stride < window scores
    only the last `stride` targets per window with the rest as context —
    the HF/reference strided protocol.
    """
    ids = np.asarray(list(token_stream), np.int32)
    if max_tokens:
        ids = ids[:max_tokens]
    stride = stride or window
    # family.forward, NOT forward_fn: ppl scores the cache-free path with
    # start offsets, which the pipeline step doesn't implement — under a
    # pp mesh GSPMD still runs this correctly (with cross-stage gathers;
    # acceptable for offline eval)
    fwd = model.family.forward

    total, count = 0.0, 0.0
    prev_end = 0
    for begin in range(0, max(len(ids) - 1, 1), stride):
        end = min(begin + window, len(ids))
        chunk = ids[end - window:end] if end >= window else ids[:end]
        pad = window - len(chunk)
        if pad:  # left-pad the first/short window; start masks the pads
            chunk = np.concatenate([np.zeros(pad, np.int32), chunk])
        # score only tokens not already scored (HF strided protocol:
        # windows overlap by window - stride as pure context)
        new_targets = min(end - prev_end, window - 1, end - 1)
        if new_targets <= 0:
            break
        valid = np.zeros(window - 1, np.float32)
        valid[window - 1 - new_targets:] = 1.0
        s, n = _window_nll(
            model.config, fwd, model.params, jnp.asarray(chunk[None]),
            jnp.asarray(valid), jnp.asarray([pad], jnp.int32),
        )
        total += float(s)
        count += float(n)
        prev_end = end
        if end == len(ids):
            break
    ppl = float(np.exp(total / max(count, 1.0)))
    if return_count:
        return ppl, int(count)
    return ppl
