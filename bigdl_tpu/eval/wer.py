"""Word error rate for whisper quality gating.

Counterpart of the reference's whisper WER harness
(dev/benchmark/whisper/run_whisper.py in /root/reference, which scores
librispeech transcriptions via the `evaluate` package's wer metric).
Here the metric is self-contained (token-level Levenshtein, the standard
WER definition: (S + D + I) / N) and `evaluate_wer` drives our whisper
family end to end: waveform -> log-mel (bigdl_tpu.audio) -> generate ->
tokenizer decode -> normalized WER against the references.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence


def edit_distance(ref: Sequence, hyp: Sequence) -> int:
    """Levenshtein distance (substitution/deletion/insertion cost 1)."""
    n, m = len(ref), len(hyp)
    if n == 0:
        return m
    prev = list(range(m + 1))
    for i in range(1, n + 1):
        cur = [i] + [0] * m
        for j in range(1, m + 1):
            cost = 0 if ref[i - 1] == hyp[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        prev = cur
    return prev[m]


def normalize_text(s: str) -> list[str]:
    """Whisper-benchmark style normalization: casefold, strip
    punctuation, split on whitespace."""
    out = []
    for w in s.lower().split():
        w = "".join(c for c in w if c.isalnum() or c == "'")
        if w:
            out.append(w)
    return out


def wer(references: Sequence[str], hypotheses: Sequence[str]) -> float:
    """Corpus-level WER: total edits / total reference words."""
    assert len(references) == len(hypotheses)
    edits = words = 0
    for ref, hyp in zip(references, hypotheses):
        r, h = normalize_text(ref), normalize_text(hyp)
        edits += edit_distance(r, h)
        words += len(r)
    return edits / max(words, 1)


def evaluate_wer(
    wconfig,
    wparams,
    samples: Sequence[tuple],  # [(waveform ndarray @16k, reference str)]
    tokenizer,
    prompt_ids: Optional[list[int]] = None,
    max_new_tokens: int = 128,
    progress: Optional[Callable[[int, int], None]] = None,
) -> dict:
    """Transcribe each sample with our whisper family and score WER.
    Returns {"wer": float, "n": int, "hypotheses": [...]}."""
    from bigdl_tpu.models import whisper as W

    hyps = []
    for i, (wave, _ref) in enumerate(samples):
        # the serving pipeline itself (whisper.transcribe_waveform): the
        # metric must score exactly what /v1/audio/transcriptions produces
        ids = W.transcribe_waveform(
            wconfig, wparams, wave, prompt_ids=prompt_ids,
            max_new_tokens=max_new_tokens,
        )
        hyps.append(tokenizer.decode(ids, skip_special_tokens=True))
        if progress:
            progress(i + 1, len(samples))
    refs = [r for _, r in samples]
    return {"wer": wer(refs, hyps), "n": len(samples), "hypotheses": hyps}
