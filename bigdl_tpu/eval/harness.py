"""lm-eval-harness adapter.

Counterpart of the reference's harness integration
(dev/benchmark/harness/ipexllm.py:38 in /root/reference, which subclasses
AutoCausalLM so `lm_eval --model ipexllm` scores quantized models). Here
the adapter implements the lm-eval 0.4 `LM` interface over a TpuModel:

    from bigdl_tpu.eval.harness import BigdlTpuLM
    lm = BigdlTpuLM(model, tokenizer)
    results = lm_eval.simple_evaluate(model=lm, tasks=["hellaswag"])

The scoring core (`score_continuations`) is plain JAX and testable
without lm-eval installed; the class registers itself with the harness
("bigdl-tpu") only when lm_eval is importable.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

try:  # lm-eval is optional (pyproject [eval] extra)
    from lm_eval.api.model import LM as _LMBase
    from lm_eval.api.registry import register_model as _register_model

    HAVE_LM_EVAL = True
except Exception:  # pragma: no cover - environment without lm-eval
    _LMBase = object
    _register_model = None
    HAVE_LM_EVAL = False


def score_continuations(
    model,
    pairs: Sequence[tuple[Sequence[int], Sequence[int]]],
    max_length: int = 2048,
    batch_size: int = 8,
) -> list[tuple[float, bool]]:
    """[(context_ids, continuation_ids)] -> [(sum logprob, is_greedy)].

    Cache-free scoring forward per bucketed batch (the same path QLoRA
    differentiates through); contexts longer than max_length - len(cont)
    are left-truncated, matching the harness convention.
    """
    from bigdl_tpu.generate import pad_prompts

    fwd = model.family.forward
    config = model.config
    results: list[Optional[tuple[float, bool]]] = [None] * len(pairs)

    order = sorted(
        range(len(pairs)),
        key=lambda i: len(pairs[i][0]) + len(pairs[i][1]),
        reverse=True,
    )
    for i0 in range(0, len(order), batch_size):
        chunk = order[i0:i0 + batch_size]
        seqs, cont_lens = [], []
        for i in chunk:
            ctx = list(pairs[i][0]) or [0]
            # over-long continuations keep their tail-most max_length-1
            # tokens (one context token must remain as the predictor);
            # note lst[-0:] is the WHOLE list, so keep must stay >= 1
            cont = list(pairs[i][1])[-(max_length - 1):]
            keep = max(max_length - len(cont), 1)
            seqs.append(ctx[-keep:] + cont)
            cont_lens.append(len(cont))
        tokens, start = pad_prompts(seqs, 0)
        B, T = tokens.shape
        logits, _ = fwd(
            config, model.params, jnp.asarray(tokens), None,
            mode="prefill", start=jnp.asarray(start),
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = np.asarray(logp)
        greedy = np.asarray(jnp.argmax(logits, axis=-1))
        for b, i in enumerate(chunk):
            n = cont_lens[b]
            # positions T-n .. T-1 hold the continuation; its token at
            # position p is predicted by logits at p-1
            tgt = tokens[b, T - n:]
            pred_rows = logp[b, T - n - 1:T - 1]
            ll = float(pred_rows[np.arange(n), tgt].sum())
            is_greedy = bool((greedy[b, T - n - 1:T - 1] == tgt).all())
            results[i] = (ll, is_greedy)
    return results  # type: ignore[return-value]


class BigdlTpuLM(_LMBase):
    """lm-eval 0.4 `LM` over a TpuModel + HF tokenizer."""

    def __init__(self, model, tokenizer, batch_size: int = 8,
                 max_length: int = 2048, max_gen_toks: int = 256):
        if HAVE_LM_EVAL:
            super().__init__()
        self.model = model
        self.tokenizer = tokenizer
        self.batch_size_ = int(batch_size)
        self.max_length = int(max_length)
        self.max_gen_toks = int(max_gen_toks)

    # -- helpers -----------------------------------------------------------
    def _encode(self, s: str) -> list[int]:
        return self.tokenizer.encode(s, add_special_tokens=False)

    @staticmethod
    def _args(req):
        """lm-eval Instance (.args) or a plain tuple/str (tests)."""
        if hasattr(req, "args"):
            return req.args
        return req if isinstance(req, tuple) else (req,)

    def _pairs(self, requests):
        out = []
        for req in requests:
            ctx, cont = self._args(req)
            ctx_ids = self._encode(ctx) if ctx else []
            cont_ids = self._encode(cont)
            out.append((ctx_ids, cont_ids))
        return out

    # -- LM interface ------------------------------------------------------
    def loglikelihood(self, requests) -> list[tuple[float, bool]]:
        return score_continuations(
            self.model, self._pairs(requests),
            max_length=self.max_length, batch_size=self.batch_size_,
        )

    def loglikelihood_rolling(self, requests) -> list[float]:
        pairs, slots = [], []
        out = [0.0] * len(requests)  # empty documents score 0, not crash
        for pos, req in enumerate(requests):
            (text,) = self._args(req)
            ids = self._encode(text)[: self.max_length]
            if len(ids) >= 2:
                pairs.append(([ids[0]], ids[1:]))  # condition on token 0
                slots.append(pos)
        for pos, (ll, _) in zip(slots, score_continuations(
            self.model, pairs, max_length=self.max_length,
            batch_size=self.batch_size_,
        )):
            out[pos] = ll
        return out

    def generate_until(self, requests) -> list[str]:
        outs = []
        for req in requests:
            ctx, kw = self._args(req)
            until = (kw or {}).get("until", [])
            max_new = int((kw or {}).get("max_gen_toks", self.max_gen_toks))
            ids = self._encode(ctx)[-self.max_length + max_new:]
            toks = self.model.generate([ids], max_new_tokens=max_new)[0]
            text = self.tokenizer.decode(
                [int(t) for t in toks], skip_special_tokens=True
            )
            for stop in until:
                if stop in text:
                    text = text.split(stop)[0]
                    break
            outs.append(text)
        return outs


if _register_model is not None:  # pragma: no cover - needs lm-eval
    @_register_model("bigdl-tpu")
    class _RegisteredBigdlTpuLM(BigdlTpuLM):
        """CLI spelling: lm_eval --model bigdl-tpu
        --model_args pretrained=<path>,load_in_low_bit=sym_int4"""

        def __init__(self, pretrained: str, load_in_low_bit: str = "sym_int4",
                     batch_size: int = 8, max_length: int = 2048, **kw):
            from transformers import AutoTokenizer

            from bigdl_tpu.api import AutoModelForCausalLM

            model = AutoModelForCausalLM.from_pretrained(
                pretrained, load_in_low_bit=load_in_low_bit
            )
            tok = AutoTokenizer.from_pretrained(pretrained)
            super().__init__(model, tok, batch_size=batch_size,
                             max_length=max_length)
