"""LongBench-style long-context evaluation.

Counterpart of the reference's LongBench harness
(/root/reference/python/llm/dev/benchmark/LongBench/pred.py): score a
model on long-document tasks by (1) middle-truncating over-long prompts
to the model's window — keeping the head and tail halves, where
LongBench puts the instruction and the question — (2) greedy-generating
an answer, (3) scoring with the task metric. The three metric families
LongBench uses most (token-F1 for QA, Rouge-L for summarization, exact
classification accuracy) are implemented here self-contained, so the
harness needs no external eval dependency.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Callable, Optional, Sequence


def middle_truncate(tokens: Sequence[int], max_len: int) -> list[int]:
    """Keep the first and last max_len/2 tokens (LongBench pred.py:
    `prompt[:half] + prompt[-half:]` on the tokenized prompt) — the
    instruction preamble and the trailing question both survive."""
    tokens = list(tokens)
    if len(tokens) <= max_len:
        return tokens
    half = max_len // 2
    return tokens[:half] + tokens[len(tokens) - (max_len - half):]


def _normalize(text: str) -> list[str]:
    """Lowercase word tokens; CJK segments split per CHARACTER (the
    LongBench reference scores zh tasks with qa_f1_zh_score, which is
    character-level — treating a run of hanzi as one token would
    degenerate F1 to exact match)."""
    text = text.lower()
    text = re.sub(r"([一-鿿])", r" \1 ", text)
    text = re.sub(r"[^a-z0-9一-鿿]+", " ", text)
    return text.split()


def qa_f1_score(prediction: str, ground_truths: Sequence[str]) -> float:
    """Token-level F1 against the best-matching reference (LongBench
    metrics.py qa_f1_score)."""
    best = 0.0
    pred = _normalize(prediction)
    for gt in ground_truths:
        ref = _normalize(gt)
        if not pred or not ref:
            best = max(best, float(pred == ref))
            continue
        common = Counter(pred) & Counter(ref)
        overlap = sum(common.values())
        if overlap == 0:
            continue
        p = overlap / len(pred)
        r = overlap / len(ref)
        best = max(best, 2 * p * r / (p + r))
    return best


def rouge_l(prediction: str, ground_truths: Sequence[str]) -> float:
    """Rouge-L F1 via longest common subsequence (LongBench rouge_score
    for summarization tasks)."""
    best = 0.0
    pred = _normalize(prediction)
    for gt in ground_truths:
        ref = _normalize(gt)
        if not pred or not ref:
            best = max(best, float(pred == ref))
            continue
        # O(len(pred)*len(ref)) LCS with a rolling row
        prev = [0] * (len(ref) + 1)
        for a in pred:
            cur = [0]
            for j, b in enumerate(ref, 1):
                cur.append(max(prev[j], cur[-1], prev[j - 1] + (a == b)))
            prev = cur
        lcs = prev[-1]
        if lcs == 0:
            continue
        p = lcs / len(pred)
        r = lcs / len(ref)
        best = max(best, 2 * p * r / (p + r))
    return best


def classification_score(prediction: str, ground_truths: Sequence[str]) -> float:
    """1.0 iff any reference appears verbatim in the prediction
    (LongBench classification_score for trec/lsht-style tasks)."""
    pred = prediction.lower()
    return float(any(gt.lower() in pred for gt in ground_truths))


METRICS: dict[str, Callable[[str, Sequence[str]], float]] = {
    "qa_f1": qa_f1_score,
    "rouge_l": rouge_l,
    "classification": classification_score,
}


def evaluate_longbench(
    model,
    tokenizer,
    samples: Sequence[dict],
    metric: str = "qa_f1",
    max_prompt_len: int = 3500,
    max_new_tokens: int = 64,
    eos_token_id: Optional[int] = None,
    stop_newline: bool = False,
    batch_size: int = 4,
) -> dict:
    """samples: [{"prompt": str, "answers": [str, ...]}, ...] (the
    flattened LongBench jsonl schema). Returns {"score", "n", "metric"}.

    model: TpuModel (api.py); tokenizer: anything with encode()/decode().
    Prompts middle-truncate to max_prompt_len; generation is greedy
    (LongBench pred.py uses do_sample=False)."""
    score_fn = METRICS[metric]
    scores: list[float] = []
    for i in range(0, len(samples), batch_size):
        chunk = samples[i:i + batch_size]
        prompts = [
            middle_truncate(tokenizer.encode(s["prompt"]), max_prompt_len)
            for s in chunk
        ]
        out = model.generate(
            prompts, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id,
        )
        for s, row in zip(chunk, out):
            ids = [int(t) for t in row]
            if eos_token_id is not None and eos_token_id in ids:
                ids = ids[: ids.index(eos_token_id)]
            text = tokenizer.decode(ids)
            if stop_newline:  # several LongBench tasks cut at first newline
                text = text.split("\n")[0]
            scores.append(score_fn(text, s["answers"]))
    return {
        "metric": metric,
        "n": len(scores),
        "score": float(sum(scores) / max(len(scores), 1)),
    }
