"""Multiple-choice accuracy (C-Eval / MMLU-style) over loglikelihoods.

Counterpart of the reference's C-Eval harness
(dev/benchmark/ceval/eval.py + evaluators/ in /root/reference): each
question scores every candidate answer's continuation loglikelihood and
picks the argmax — the standard zero-/few-shot MCQ protocol. Reuses the
lm-eval scoring core (eval/harness.score_continuations), so quantized
models score through exactly the serving forward.

Example item (C-Eval row):
    {"question": "...", "choices": ["A ...", "B ...", ...], "answer": 2}
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

from bigdl_tpu.eval.harness import score_continuations


def mcq_accuracy(
    model,
    tokenizer,
    items: Sequence[dict],
    prompt_template: str = "{question}\n答案：",
    normalize_length: bool = False,
    batch_size: int = 8,
    max_length: int = 2048,
    progress: Optional[Callable[[int, int], None]] = None,
) -> dict:
    """Returns {"accuracy": float, "n": int, "predictions": [...]}.

    normalize_length=True divides each choice's loglikelihood by its
    token count (the acc_norm variant) — helps when options differ a lot
    in length."""
    pairs = []
    spans = []  # (start, n_choices, answer)
    for item in items:
        ctx = tokenizer.encode(
            prompt_template.format(**item), add_special_tokens=False
        )
        start = len(pairs)
        for choice in item["choices"]:
            cont = tokenizer.encode(str(choice), add_special_tokens=False)
            pairs.append((ctx, cont or [0]))
        spans.append((start, len(item["choices"]), int(item["answer"])))

    scores = score_continuations(
        model, pairs, max_length=max_length, batch_size=batch_size
    )
    correct = 0
    preds = []
    for i, (start, n, answer) in enumerate(spans):
        lls = [scores[start + j][0] for j in range(n)]
        if normalize_length:
            lls = [ll / max(len(pairs[start + j][1]), 1)
                   for j, ll in enumerate(lls)]
        pred = max(range(n), key=lambda j: lls[j])
        preds.append(pred)
        correct += int(pred == answer)
        if progress:
            progress(i + 1, len(spans))
    return {
        "accuracy": correct / max(len(spans), 1),
        "n": len(spans),
        "predictions": preds,
    }


def load_ceval_csv(path: str) -> list[dict]:
    """Parse a C-Eval val CSV (id,question,A,B,C,D,answer) into items."""
    import csv

    items = []
    with open(path, newline="", encoding="utf-8") as f:
        for row in csv.DictReader(f):
            items.append({
                "question": row["question"],
                "choices": [row["A"], row["B"], row["C"], row["D"]],
                "answer": "ABCD".index(row["answer"].strip()),
            })
    return items
