"""GGUF export — write llama.cpp-compatible model files.

Output-side counterpart of the reference's `llm_convert`
(/root/reference/python/llm/src/ipex_llm/convert_model.py:31 →
ggml/convert_model.py: HF checkpoint -> native ggml/gguf file) and the
inverse of convert/gguf.py's importer. The writer emits GGUF v3 with the
llama.cpp metadata keys our own `config_from_gguf` reads, so
export -> `from_gguf` round-trips bit-exactly for the quantized types;
llama.cpp itself additionally needs `tokenizer.ggml.*` metadata, which
the caller supplies via `extra_metadata` (we have no tokenizer model —
the reference reads it from the source checkpoint the same way).

Block encoders mirror the importer's dequant layouts exactly (q8_0 =
[d f16][32 i8]; q4_0 = [d f16][16 bytes, element j in the low nibble and
j+16 in the high]); k-quants reuse quant/kquants.py's llama.cpp-layout
encoders. The llama/mistral rope row-permute (LlamaModel.permute in
llama.cpp's converter) is applied on export and undone by the importer.
"""

from __future__ import annotations

import io
import struct
from typing import Any, Optional

import numpy as np

from bigdl_tpu.convert.gguf import (
    GGML_BF16, GGML_F16, GGML_F32, GGML_Q4_0, GGML_Q8_0,
    GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K,
    GGUF_MAGIC, _V_ARR, _V_BOOL, _V_F32, _V_I32, _V_I64, _V_STR, _V_U32,
    _V_U64,
)
from bigdl_tpu.models.config import ModelConfig

ALIGN = 32

_KQ_EXPORT = {"q2_k": GGML_Q2_K, "q3_k": GGML_Q3_K, "q4_k": GGML_Q4_K,
              "q5_k": GGML_Q5_K, "q6_k": GGML_Q6_K}


# ---------------------------------------------------------------------------
# block encoders (exact inverses of convert/gguf.py's dequants)
# ---------------------------------------------------------------------------

def encode_q8_0(x: np.ndarray) -> np.ndarray:
    """[..., K] f32 -> [..., K/32, 34] uint8."""
    xb = np.asarray(x, np.float32).reshape(*x.shape[:-1], -1, 32)
    absmax = np.abs(xb).max(axis=-1)
    d = (absmax / 127.0).astype(np.float32)
    inv = np.where(d > 0, 1.0 / np.where(d == 0, 1, d), 0.0)
    q = np.clip(np.round(xb * inv[..., None]), -127, 127).astype(np.int8)
    out = np.empty((*q.shape[:-1], 34), np.uint8)
    out[..., 0:2] = d.astype(np.float16)[..., None].view(np.uint8)
    out[..., 2:34] = q.view(np.uint8)
    return out


def encode_q4_0(x: np.ndarray) -> np.ndarray:
    """[..., K] f32 -> [..., K/32, 18] uint8 (llama.cpp q4_0: the scale
    divides by the SIGNED max-magnitude element over -8)."""
    xb = np.asarray(x, np.float32).reshape(*x.shape[:-1], -1, 32)
    amax_idx = np.abs(xb).argmax(axis=-1)
    signed_max = np.take_along_axis(xb, amax_idx[..., None], axis=-1)[..., 0]
    d = (signed_max / -8.0).astype(np.float32)
    inv = np.where(d != 0, 1.0 / np.where(d == 0, 1, d), 0.0)
    q = np.clip(np.round(xb * inv[..., None]) + 8, 0, 15).astype(np.uint8)
    out = np.empty((*q.shape[:-1], 18), np.uint8)
    out[..., 0:2] = d.astype(np.float16)[..., None].view(np.uint8)
    out[..., 2:18] = q[..., :16] | (q[..., 16:] << 4)
    return out


def encode_tensor(x: np.ndarray, ggml_type: int) -> bytes:
    if ggml_type == GGML_F32:
        return np.asarray(x, np.float32).tobytes()
    if ggml_type == GGML_F16:
        return np.asarray(x, np.float16).tobytes()
    if ggml_type == GGML_BF16:
        import jax.numpy as jnp

        return np.asarray(jnp.asarray(x, jnp.bfloat16)).tobytes()
    if ggml_type == GGML_Q8_0:
        return encode_q8_0(x).tobytes()
    if ggml_type == GGML_Q4_0:
        return encode_q4_0(x).tobytes()
    for name, t in _KQ_EXPORT.items():
        if t == ggml_type:
            if x.shape[-1] % 256:
                raise ValueError(
                    f"k-quant export needs the last dim divisible by 256; "
                    f"got {x.shape} — use q8_0/q4_0 for this tensor"
                )
            from bigdl_tpu.quant import kquants

            enc = getattr(kquants, f"quantize_{name}")
            return enc(np.asarray(x, np.float32)).tobytes()
    raise NotImplementedError(f"gguf export for ggml type {ggml_type}")


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

def _w_str(f, s: str) -> None:
    b = s.encode()
    f.write(struct.pack("<Q", len(b)))
    f.write(b)


def _w_value(f, v: Any) -> None:
    if isinstance(v, bool):
        f.write(struct.pack("<I", _V_BOOL))
        f.write(struct.pack("<?", v))
    elif isinstance(v, int):
        if 0 <= v < 2 ** 32:
            f.write(struct.pack("<II", _V_U32, v))
        elif v >= 0:
            f.write(struct.pack("<I", _V_U64))
            f.write(struct.pack("<Q", v))
        else:
            f.write(struct.pack("<Ii", _V_I32, v))
    elif isinstance(v, float):
        f.write(struct.pack("<If", _V_F32, v))
    elif isinstance(v, str):
        f.write(struct.pack("<I", _V_STR))
        _w_str(f, v)
    elif isinstance(v, (list, tuple)):
        f.write(struct.pack("<I", _V_ARR))
        if all(isinstance(e, str) for e in v):
            f.write(struct.pack("<IQ", _V_STR, len(v)))
            for e in v:
                _w_str(f, e)
        elif all(isinstance(e, int) for e in v):
            # element type from the value range, validated BEFORE any header
            # bytes hit the disk (a mid-write struct.error would leave a
            # truncated file): i32 when everything fits, else i64 when any
            # element is negative, else u64.
            if all(-2 ** 31 <= e < 2 ** 31 for e in v):
                etype, fmt = _V_I32, "<i"
            elif any(e < 0 for e in v):
                if not all(-2 ** 63 <= e < 2 ** 63 for e in v):
                    raise ValueError(f"int list out of i64 range: {v!r}")
                etype, fmt = _V_I64, "<q"
            else:
                if not all(e < 2 ** 64 for e in v):
                    raise ValueError(f"int list out of u64 range: {v!r}")
                etype, fmt = _V_U64, "<Q"
            f.write(struct.pack("<IQ", etype, len(v)))
            for e in v:
                f.write(struct.pack(fmt, e))
        else:
            f.write(struct.pack("<IQ", _V_F32, len(v)))
            for e in v:
                f.write(struct.pack("<f", float(e)))
    else:
        raise TypeError(f"gguf metadata value {v!r}")


def _payload_size(shape: tuple, ggml_type: int) -> int:
    from bigdl_tpu.convert.gguf import _BLOCK

    elems, nbytes = _BLOCK[ggml_type]
    n = 1
    for d in shape:
        n *= d
    assert n % elems == 0, (shape, ggml_type)
    return n // elems * nbytes


def write_gguf(
    path: str,
    metadata: dict[str, Any],
    tensors: dict[str, tuple[tuple, int, Any]],  # name -> (shape, type, get)
    *,
    faults=None,
) -> None:
    """Write a GGUF v3 file STREAMING: payload sizes are computed from
    (shape, ggml_type) alone, the directory is written first, and each
    tensor is materialized (get() -> f32 array), encoded, written, and
    dropped — peak host memory stays ~one tensor, not the model
    (a 7B export would otherwise hold ~35 GB of f32 + blocks).

    The file lands through the atomic tmp+fsync+rename protocol
    (utils/durability.py): a kill mid-export — or a mid-stream encoder
    error — never leaves a truncated .gguf where a previous export
    stood. `faults` threads a DiskFaultInjector through the write
    (tests only)."""
    metadata = dict(metadata)
    metadata["general.alignment"] = ALIGN

    # serialize metadata in memory first: a bad value (out-of-range int,
    # unsupported type) raises before the output file is even created,
    # never leaving a truncated GGUF on disk
    meta_buf = io.BytesIO()
    for k, v in metadata.items():
        _w_str(meta_buf, k)
        _w_value(meta_buf, v)

    def _write_body(f) -> None:
        f.write(struct.pack("<IIQQ", GGUF_MAGIC, 3, len(tensors), len(metadata)))
        f.write(meta_buf.getvalue())
        offset = 0
        for name, (shape, t, _get) in tensors.items():
            _w_str(f, name)
            dims = tuple(reversed(shape))  # innermost-first on disk
            f.write(struct.pack("<I", len(dims)))
            f.write(struct.pack(f"<{len(dims)}Q", *dims))
            f.write(struct.pack("<IQ", t, offset))
            size = _payload_size(shape, t)
            offset += (size + ALIGN - 1) // ALIGN * ALIGN
        pos = f.tell()
        f.write(b"\x00" * ((pos + ALIGN - 1) // ALIGN * ALIGN - pos))
        for name, (shape, t, get) in tensors.items():
            arr = get()
            assert tuple(arr.shape) == tuple(shape), (name, arr.shape, shape)
            data = encode_tensor(arr, t)
            assert len(data) == _payload_size(shape, t), name
            f.write(data)
            pad = (len(data) + ALIGN - 1) // ALIGN * ALIGN - len(data)
            f.write(b"\x00" * pad)

    from bigdl_tpu.utils.durability import atomic_write

    atomic_write(path, _write_body, faults=faults)


# ---------------------------------------------------------------------------
# model export (llama-family)
# ---------------------------------------------------------------------------

def _permute_rows(n_heads: int, n_rows: int) -> np.ndarray:
    """llama.cpp's HF->gguf rope row permute (exact inverse of the
    importer's _unpermute_rows)."""
    d = n_rows // n_heads
    idx = np.arange(n_rows).reshape(n_heads, 2, d // 2)
    return idx.transpose(0, 2, 1).reshape(-1)


_GGML_FOR_QTYPE = {
    "q8_0": GGML_Q8_0, "q4_0": GGML_Q4_0, "f16": GGML_F16,
    "f32": GGML_F32, "bf16": GGML_BF16,
    **_KQ_EXPORT,
}


def export_gguf(
    config: ModelConfig,
    params: dict,
    path: str,
    qtype: str = "q8_0",
    name: str = "bigdl-tpu-export",
    extra_metadata: Optional[dict] = None,
    faults=None,
) -> None:
    """Export a llama-family param tree to GGUF (weights quantize to
    `qtype`; norms stay f32). QTensor leaves dequantize first — GGUF
    block layouts don't match our packed layout except for k-quants,
    and requantizing through the encoder keeps the file self-contained."""
    import jax.numpy as jnp

    from bigdl_tpu.quant import QTensor

    arch = {"qwen2": "qwen2", "mistral": "mistral"}.get(
        config.model_type, "llama"
    )
    # GGUF's llama/qwen2 tensor sets carry exactly the vanilla layout —
    # refuse configs whose weights would be silently dropped or whose
    # layout the name map can't express (the reference's llm_convert is
    # likewise per-architecture)
    unsupported = [
        flag for flag, on in (
            ("qk_norm", config.qk_norm),
            ("attention_out_bias", config.attention_out_bias),
            ("post_attn_norm", config.post_attn_norm),
            ("mlp_bias", config.mlp_bias),
            ("norm_bias", config.norm_bias),
            ("moe", config.is_moe),
            ("non-gated mlp", not config.gated_mlp),
            ("alibi", config.alibi),
            ("learned_positions", config.learned_positions),
            ("mla", config.kv_lora_rank is not None),
        ) if on
    ]
    if unsupported:
        raise NotImplementedError(
            f"gguf export covers vanilla llama/mistral/qwen2 layouts; "
            f"this config needs: {', '.join(unsupported)}"
        )
    t = _GGML_FOR_QTYPE[qtype]

    def dense(w):
        def get() -> np.ndarray:
            if isinstance(w, QTensor):
                return np.asarray(w.dequantize(jnp.float32))
            return np.asarray(jnp.asarray(w, jnp.float32))

        return get

    def leaf_shape(w) -> tuple:
        return tuple(w.shape)

    # lazy getters: write_gguf materializes one tensor at a time
    tensors: dict[str, tuple[tuple, int, Any]] = {}

    permute = arch in ("llama", "mistral")  # qwen2 stays in HF row order
    Hq, Hkv = config.num_attention_heads, config.num_key_value_heads
    lay = params["layers"]

    def layer_leaf(key: str, i: int, permute_heads=None):
        def get() -> np.ndarray:
            w = lay[key]
            if isinstance(w, QTensor):
                arr = np.asarray(
                    w.map_arrays(lambda a: a[i]).dequantize(jnp.float32)
                )
            else:
                arr = np.asarray(jnp.asarray(w[i], jnp.float32))
            if permute_heads is not None:
                arr = arr[_permute_rows(permute_heads, arr.shape[0])]
            return arr

        return get

    def layer_shape(key: str) -> tuple:
        w = lay[key]
        shape = tuple(w.shape[1:])
        return shape

    if "wqkv" in lay or "w_gateup" in lay:
        raise ValueError(
            "export needs the unmerged layout; call "
            "family.unmerge_fused_params(params, config) first"
        )

    def put(gname, key, i, ggml_type, permute_heads=None):
        tensors[gname] = (
            layer_shape(key), ggml_type, layer_leaf(key, i, permute_heads)
        )

    for i in range(config.num_hidden_layers):
        p = f"blk.{i}."
        put(p + "attn_norm.weight", "attn_norm", i, GGML_F32)
        put(p + "ffn_norm.weight", "mlp_norm", i, GGML_F32)
        put(p + "attn_q.weight", "wq", i, t, Hq if permute else None)
        put(p + "attn_k.weight", "wk", i, t, Hkv if permute else None)
        put(p + "attn_v.weight", "wv", i, t)
        put(p + "attn_output.weight", "wo", i, t)
        put(p + "ffn_gate.weight", "w_gate", i, t)
        put(p + "ffn_up.weight", "w_up", i, t)
        put(p + "ffn_down.weight", "w_down", i, t)
        if config.attention_bias:
            put(p + "attn_q.bias", "bq", i, GGML_F32, Hq if permute else None)
            put(p + "attn_k.bias", "bk", i, GGML_F32, Hkv if permute else None)
            put(p + "attn_v.bias", "bv", i, GGML_F32)

    tensors["token_embd.weight"] = (
        leaf_shape(params["embed"]), t, dense(params["embed"])
    )
    tensors["output_norm.weight"] = (
        leaf_shape(params["final_norm"]), GGML_F32, dense(params["final_norm"])
    )
    if "lm_head" in params:
        tensors["output.weight"] = (
            leaf_shape(params["lm_head"]), t, dense(params["lm_head"])
        )

    md: dict[str, Any] = {
        "general.architecture": arch,
        "general.name": name,
        f"{arch}.embedding_length": config.hidden_size,
        f"{arch}.feed_forward_length": config.intermediate_size,
        f"{arch}.block_count": config.num_hidden_layers,
        f"{arch}.attention.head_count": Hq,
        f"{arch}.attention.head_count_kv": Hkv,
        f"{arch}.attention.layer_norm_rms_epsilon": float(config.rms_norm_eps),
        f"{arch}.rope.freq_base": float(config.rope_theta),
        f"{arch}.context_length": config.max_position_embeddings,
    }
    if config.head_dim is not None:
        md[f"{arch}.attention.key_length"] = config.head_dim
        md[f"{arch}.attention.value_length"] = config.head_dim
    rs = config.rope_scaling_dict
    if rs:
        md[f"{arch}.rope.scaling.type"] = str(
            rs.get("rope_type", rs.get("type", "linear"))
        )
        if rs.get("factor"):
            md[f"{arch}.rope.scaling.factor"] = float(rs["factor"])
        if rs.get("original_max_position_embeddings"):
            md[f"{arch}.rope.scaling.original_context_length"] = int(
                rs["original_max_position_embeddings"]
            )
    if extra_metadata:
        md.update(extra_metadata)
    write_gguf(path, md, tensors, faults=faults)
