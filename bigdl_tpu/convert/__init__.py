"""Checkpoint conversion engine (reference: transformers/convert.py +
transformers/model.py — `from_pretrained`, `save_low_bit`, `load_low_bit`)."""

from bigdl_tpu.convert.hf import (
    params_from_state_dict,
    load_hf_checkpoint,
    layer_tensors,
    top_tensors,
)
from bigdl_tpu.convert.low_bit import save_low_bit, load_low_bit, verify_low_bit

__all__ = [
    "params_from_state_dict",
    "load_hf_checkpoint",
    "layer_tensors",
    "top_tensors",
    "save_low_bit",
    "load_low_bit",
    "verify_low_bit",
]
