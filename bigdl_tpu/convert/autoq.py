"""AWQ / GPTQ checkpoint import.

Equivalent of the reference's GPTQ/AWQ ingest
(`transformers/convert.py:379-455` convert_gptq unpack→requant to ggml
asym_int4; `transformers/awq/` layer replacement in /root/reference),
TPU-shaped: the int32-packed codes are unpacked with numpy and mapped
**exactly** into our asym_int4 QTensor when the quantization group size
is a multiple of our 32-element block (the usual 128): per-group
(scale, zero) become per-block (d, m) with

    gptq/awq:  w = (code - zero) * scale
    asym_int4: w = code * d + m        →  d = scale, m = -zero * scale

so codes are carried bit-for-bit. Non-divisible group sizes or
activation-ordered (g_idx-shuffled) checkpoints fall back to fp32
dequantization + requantization.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def _unpack_int32_nibbles(packed: np.ndarray, axis: int, order: np.ndarray) -> np.ndarray:
    """int32 array → uint8 4-bit codes expanded 8x along `axis`, nibble
    positions read in `order`."""
    shifts = (order * 4).astype(np.uint32)
    p = packed.astype(np.uint32)
    p = np.expand_dims(p, axis + 1 if axis >= 0 else packed.ndim + axis + 1)
    shape = [1] * p.ndim
    shape[axis + 1 if axis >= 0 else p.ndim + axis] = 8
    codes = (p >> shifts.reshape(shape)) & 0xF
    new_shape = list(packed.shape)
    new_shape[axis] *= 8
    return codes.reshape(new_shape).astype(np.uint8)


_GPTQ_ORDER = np.arange(8)  # sequential nibbles
_AWQ_ORDER = np.array([0, 4, 1, 5, 2, 6, 3, 7])  # AWQ interleaved packing


def unpack_gptq(
    qweight: np.ndarray,  # int32 [in/8, out]
    qzeros: np.ndarray,  # int32 [groups, out/8]
    scales: np.ndarray,  # fp16/fp32 [groups, out]
    bits: int = 4,
    v1_zero_offset: bool = True,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Returns (codes [out, in] uint8, scales [out, groups] f32,
    zeros [out, groups] f32). GPTQ v1 stores zeros-1 (the +1 is re-added
    here); v2 ('checkpoint_format: gptq_v2') stores them raw."""
    assert bits == 4, "only 4-bit GPTQ supported"
    codes = _unpack_int32_nibbles(qweight, axis=0, order=_GPTQ_ORDER)  # [in, out]
    zeros = _unpack_int32_nibbles(qzeros, axis=1, order=_GPTQ_ORDER)  # [groups, out]
    z = zeros.astype(np.float32)
    if v1_zero_offset:
        z = z + 1.0
    return codes.T, scales.astype(np.float32).T, z.T


def unpack_awq(
    qweight: np.ndarray,  # int32 [in, out/8]
    qzeros: np.ndarray,  # int32 [in/group, out/8]
    scales: np.ndarray,  # fp16 [in/group, out]
    bits: int = 4,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    assert bits == 4, "only 4-bit AWQ supported"
    codes = _unpack_int32_nibbles(qweight, axis=1, order=_AWQ_ORDER)  # [in, out]
    zeros = _unpack_int32_nibbles(qzeros, axis=1, order=_AWQ_ORDER)  # [groups, out]
    return codes.T, scales.astype(np.float32).T, zeros.astype(np.float32).T


def codes_to_qtensor(
    codes: np.ndarray,  # [out, in] uint8 4-bit
    scales: np.ndarray,  # [out, groups] f32
    zeros: np.ndarray,  # [out, groups] f32
    group_size: int,
):
    """Exact mapping into asym_int4 (block 32) when group_size % 32 == 0."""
    from jax import numpy as jnp

    from bigdl_tpu.quant import QTensor
    from bigdl_tpu.quant.numerics import pack_nibbles

    out, k = codes.shape
    assert group_size % 32 == 0 and k % group_size == 0
    rep = group_size // 32
    d = np.repeat(scales, rep, axis=1).astype(np.float16)  # [out, k/32]
    m = np.repeat(-zeros * scales, rep, axis=1).astype(np.float16)
    data = np.asarray(pack_nibbles(jnp.asarray(codes)))
    return QTensor(
        data=jnp.asarray(data), scales=jnp.asarray(d),
        mins=jnp.asarray(m), qtype="asym_int4",
    )


def dequantize_to_fp32(
    codes: np.ndarray, scales: np.ndarray, zeros: np.ndarray, group_size: int,
    g_idx: Optional[np.ndarray] = None,
) -> np.ndarray:
    """[out, in] fp32; honors act-order g_idx when present."""
    out, k = codes.shape
    if g_idx is not None:
        g = np.asarray(g_idx)
    else:
        g = np.arange(k) // group_size
    return (codes.astype(np.float32) - zeros[:, g]) * scales[:, g]


def _trivial_g_idx(g_idx: Optional[np.ndarray], group_size: int, k: int) -> bool:
    if g_idx is None:
        return True
    return bool(np.array_equal(np.asarray(g_idx), np.arange(k) // group_size))


class QuantCheckpointAdapter:
    """Makes a GPTQ/AWQ safetensors checkpoint look like a dense one.

    `get_weight(name)` returns an exact QTensor for 1:1-mapped linear
    weights when possible, else a dequantized fp32 array; `get(name)`
    always returns fp32 (for family builders that slice/merge tensors,
    e.g. phi3 fused qkv).
    """

    def __init__(self, get_tensor, quant_config: dict):
        self._get = get_tensor
        self.method = quant_config.get("quant_method", "gptq")
        self.bits = quant_config.get("bits", quant_config.get("w_bit", 4))
        self.group_size = quant_config.get(
            "group_size", quant_config.get("q_group_size", 128)
        )
        self.v1_offset = quant_config.get("checkpoint_format", "gptq") != "gptq_v2"
        if self.method not in ("gptq", "awq"):
            raise NotImplementedError(f"quant_method {self.method!r}")
        if self.bits != 4:
            raise NotImplementedError(f"{self.method} bits={self.bits} (need 4)")

    def _unpack(self, base: str):
        qweight = self._get(base + ".qweight")
        qzeros = self._get(base + ".qzeros")
        scales = self._get(base + ".scales")
        try:
            g_idx = self._get(base + ".g_idx")
        except KeyError:
            g_idx = None
        if self.method == "gptq":
            c, s, z = unpack_gptq(
                qweight, qzeros, scales, self.bits, self.v1_offset
            )
        else:
            c, s, z = unpack_awq(qweight, qzeros, scales, self.bits)
        return c, s, z, g_idx

    def is_quantized(self, name: str) -> bool:
        """name is '<module>.weight' of a packed linear?"""
        base = name.removesuffix(".weight")
        try:
            self._get(base + ".qweight")
            return True
        except KeyError:
            return False

    def get_weight(self, name: str):
        """QTensor (exact) or fp32 ndarray for '<module>.weight'."""
        base = name.removesuffix(".weight")
        c, s, z, g_idx = self._unpack(base)
        if self.group_size % 32 == 0 and _trivial_g_idx(
            g_idx, self.group_size, c.shape[1]
        ):
            return codes_to_qtensor(c, s, z, self.group_size)
        return dequantize_to_fp32(c, s, z, self.group_size, g_idx)

    def get(self, name: str) -> np.ndarray:
        base = name.removesuffix(".weight")
        if name.endswith(".weight") and self.is_quantized(name):
            c, s, z, g_idx = self._unpack(base)
            return dequantize_to_fp32(c, s, z, self.group_size, g_idx)
        return self._get(name)
