"""GGUF checkpoint import.

TPU-native counterpart of the reference's GGUF stack
(`transformers/gguf/gguf.py` GGUFFileLoader binary parser + per-family
weight mappers in `transformers/gguf/models/*.py`, dispatched by
`gguf/api.py:30-80` in /root/reference): parse the GGUF v2/v3 container,
dequantize or — where the layout allows — **directly repack** ggml blocks
into our QTensor formats without a dequant/requant round trip:

- Q4_0 → sym_int4: same 32-block absmax/-8 numerics; only the nibble
  order differs (ggml: element j & j+16 share byte j; ours: 2i/2i+1).
- Q4_1 → asym_int4 (d·q + m, identical numerics, nibble reorder).
- Q8_0 → sym_int8 (bytes carried over unchanged).
- Q5_0/Q5_1 → sym_int5/asym_int5 (high bit unpacked from qh; sym_int5
  re-packs into the 4+1 bit-plane layout the fused GEMV reads).
- K-quants (Q2_K..Q6_K) repack bit-exactly into the TPU planar layout
  (quant/kq_planar.py) consumed by the fused Pallas GEMV kernels;
  remaining float tensors are dequantized to fp32 and re-quantized to
  the requested qtype.

The llama.cpp converter permutes Wq/Wk rows (interleaved→half rope
conversion); import un-permutes them (same fix the reference applies in
gguf/models/llama.py). Row permutation commutes with our per-row block
quantization, so repacked tensors are permuted on the packed data.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any, BinaryIO, Callable, Optional

import numpy as np

from bigdl_tpu.models.config import ModelConfig

GGUF_MAGIC = 0x46554747  # "GGUF" little-endian

# ggml tensor types (ggml.h enum ggml_type)
GGML_F32, GGML_F16 = 0, 1
GGML_Q4_0, GGML_Q4_1 = 2, 3
GGML_Q5_0, GGML_Q5_1 = 6, 7
GGML_Q8_0 = 8
GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K = 10, 11, 12, 13, 14
GGML_IQ2_XXS, GGML_IQ2_XS = 16, 17
GGML_IQ1_S, GGML_IQ1_M = 19, 29
GGML_BF16 = 30

_TYPE_NAMES = {
    GGML_F32: "f32", GGML_F16: "f16", GGML_BF16: "bf16",
    GGML_Q4_0: "q4_0", GGML_Q4_1: "q4_1", GGML_Q5_0: "q5_0",
    GGML_Q5_1: "q5_1", GGML_Q8_0: "q8_0", GGML_Q2_K: "q2_k",
    GGML_Q3_K: "q3_k", GGML_Q4_K: "q4_k", GGML_Q5_K: "q5_k",
    GGML_Q6_K: "q6_k", GGML_IQ2_XXS: "iq2_xxs", GGML_IQ2_XS: "iq2_xs",
    GGML_IQ1_S: "iq1_s", GGML_IQ1_M: "iq1_m",
}

from bigdl_tpu.quant.qtypes import KQUANT_LAYOUT  # numpy-only module

_KQUANT_TYPES = {GGML_Q2_K: "q2_k", GGML_Q3_K: "q3_k", GGML_Q4_K: "q4_k",
                 GGML_Q5_K: "q5_k", GGML_Q6_K: "q6_k"}

# (block_elems, block_bytes); k-quant sizes come from the single layout
# table in quant/qtypes.py
_BLOCK = {
    GGML_F32: (1, 4), GGML_F16: (1, 2), GGML_BF16: (1, 2),
    GGML_Q4_0: (32, 18), GGML_Q4_1: (32, 20),
    GGML_Q5_0: (32, 22), GGML_Q5_1: (32, 24),
    GGML_Q8_0: (32, 34),
    **{t: (256, KQUANT_LAYOUT[n][0]) for t, n in _KQUANT_TYPES.items()},
    # IQ formats (importance quants; decoded via quant/iq_quants.py and
    # re-quantized on load — no native runtime layout)
    GGML_IQ2_XXS: (256, 66), GGML_IQ2_XS: (256, 74),
    GGML_IQ1_S: (256, 50), GGML_IQ1_M: (256, 56),
}

# metadata value types
_V_U8, _V_I8, _V_U16, _V_I16, _V_U32, _V_I32, _V_F32, _V_BOOL = range(8)
_V_STR, _V_ARR, _V_U64, _V_I64, _V_F64 = 8, 9, 10, 11, 12
_SCALAR_FMT = {
    _V_U8: "<B", _V_I8: "<b", _V_U16: "<H", _V_I16: "<h",
    _V_U32: "<I", _V_I32: "<i", _V_F32: "<f", _V_U64: "<Q",
    _V_I64: "<q", _V_F64: "<d",
}


def _read_str(f: BinaryIO) -> str:
    (n,) = struct.unpack("<Q", f.read(8))
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype == _V_BOOL:
        return bool(f.read(1)[0])
    if vtype == _V_STR:
        return _read_str(f)
    if vtype == _V_ARR:
        (etype,) = struct.unpack("<I", f.read(4))
        (count,) = struct.unpack("<Q", f.read(8))
        return [_read_value(f, etype) for _ in range(count)]
    fmt = _SCALAR_FMT[vtype]
    (v,) = struct.unpack(fmt, f.read(struct.calcsize(fmt)))
    return v


@dataclass
class GGUFTensorInfo:
    name: str
    shape: tuple[int, ...]  # logical shape, row-major (numpy order)
    ggml_type: int
    offset: int  # relative to data section start

    @property
    def type_name(self) -> str:
        return _TYPE_NAMES.get(self.ggml_type, f"type{self.ggml_type}")


class GGUFReader:
    """Parses header/metadata/tensor directory; tensor data is read lazily
    from the underlying file (equivalent of the reference's GGUFFileLoader,
    gguf/gguf.py)."""

    def __init__(self, path: str):
        self.path = path
        self.metadata: dict[str, Any] = {}
        self.tensors: dict[str, GGUFTensorInfo] = {}
        with open(path, "rb") as f:
            magic, version = struct.unpack("<II", f.read(8))
            if magic != GGUF_MAGIC:
                raise ValueError(f"{path}: not a GGUF file (magic {magic:#x})")
            if version < 2:
                raise ValueError(f"GGUF v{version} unsupported (need >= 2)")
            self.version = version
            n_tensors, n_kv = struct.unpack("<QQ", f.read(16))
            for _ in range(n_kv):
                key = _read_str(f)
                (vtype,) = struct.unpack("<I", f.read(4))
                self.metadata[key] = _read_value(f, vtype)
            for _ in range(n_tensors):
                name = _read_str(f)
                (n_dims,) = struct.unpack("<I", f.read(4))
                dims = struct.unpack(f"<{n_dims}Q", f.read(8 * n_dims))
                ggml_type, = struct.unpack("<I", f.read(4))
                offset, = struct.unpack("<Q", f.read(8))
                # GGUF dims are innermost-first; numpy shape is the reverse
                self.tensors[name] = GGUFTensorInfo(
                    name, tuple(reversed(dims)), ggml_type, offset
                )
            align = self.metadata.get("general.alignment", 32)
            pos = f.tell()
            self.data_start = (pos + align - 1) // align * align

    @property
    def architecture(self) -> str:
        return self.metadata.get("general.architecture", "llama")

    def raw_blocks(self, name: str) -> np.ndarray:
        """[n_rows..., n_blocks, block_bytes] uint8 for quantized types."""
        info = self.tensors[name]
        elems, nbytes = _BLOCK[info.ggml_type]
        k = info.shape[-1]
        assert k % elems == 0, (name, info.shape, info.type_name)
        n_blocks_total = int(np.prod(info.shape)) // elems
        with open(self.path, "rb") as f:
            f.seek(self.data_start + info.offset)
            raw = np.frombuffer(f.read(n_blocks_total * nbytes), np.uint8)
        return raw.reshape(*info.shape[:-1], k // elems, nbytes)

    def dequantize(self, name: str) -> np.ndarray:
        """Full fp32 tensor, any supported ggml type."""
        info = self.tensors[name]
        t = info.ggml_type
        if t in (GGML_F32, GGML_F16, GGML_BF16):
            with open(self.path, "rb") as f:
                f.seek(self.data_start + info.offset)
                n = int(np.prod(info.shape))
                if t == GGML_F32:
                    arr = np.frombuffer(f.read(4 * n), np.float32)
                elif t == GGML_F16:
                    arr = np.frombuffer(f.read(2 * n), np.float16).astype(np.float32)
                else:  # bf16
                    raw = np.frombuffer(f.read(2 * n), np.uint16).astype(np.uint32)
                    arr = (raw << 16).view(np.float32)
            return arr.reshape(info.shape).copy()
        blocks = self.raw_blocks(name)
        fn = _DEQUANT[t]
        return fn(blocks).reshape(info.shape)


# ---------------------------------------------------------------------------
# block decoders (vectorized; layouts from ggml's dequantize_row_* kernels,
# re-derived — the byte order is a stable public format)
# ---------------------------------------------------------------------------

def _f16(blocks: np.ndarray, off: int) -> np.ndarray:
    return (
        blocks[..., off:off + 2].copy().view(np.float16)[..., 0].astype(np.float32)
    )


def _deq_q4_0(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks, 0)
    qs = blocks[..., 2:18]
    lo = (qs & 0xF).astype(np.float32) - 8.0
    hi = (qs >> 4).astype(np.float32) - 8.0
    vals = np.concatenate([lo, hi], axis=-1)  # elements 0..15, 16..31
    return vals * d[..., None]


def _deq_q4_1(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks, 0)
    m = _f16(blocks, 2)
    qs = blocks[..., 4:20]
    vals = np.concatenate(
        [(qs & 0xF).astype(np.float32), (qs >> 4).astype(np.float32)], axis=-1
    )
    return vals * d[..., None] + m[..., None]


def _q5_high_bits(blocks: np.ndarray, off: int) -> np.ndarray:
    qh = blocks[..., off:off + 4].copy().view(np.uint32)[..., 0]
    shifts = np.arange(32, dtype=np.uint32)
    return ((qh[..., None] >> shifts) & 1).astype(np.uint8)  # [..., 32]


def _deq_q5_0(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks, 0)
    h = _q5_high_bits(blocks, 2)
    qs = blocks[..., 6:22]
    lo = (qs & 0xF) | (h[..., :16] << 4)
    hi = (qs >> 4) | (h[..., 16:] << 4)
    vals = np.concatenate([lo, hi], axis=-1).astype(np.float32) - 16.0
    return vals * d[..., None]


def _deq_q5_1(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks, 0)
    m = _f16(blocks, 2)
    h = _q5_high_bits(blocks, 4)
    qs = blocks[..., 8:24]
    lo = (qs & 0xF) | (h[..., :16] << 4)
    hi = (qs >> 4) | (h[..., 16:] << 4)
    vals = np.concatenate([lo, hi], axis=-1).astype(np.float32)
    return vals * d[..., None] + m[..., None]


def _deq_q8_0(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks, 0)
    qs = blocks[..., 2:34].copy().view(np.int8).astype(np.float32)
    return qs * d[..., None]


def _deq_q4_k(blocks: np.ndarray) -> np.ndarray:
    d = _f16(blocks, 0)
    dmin = _f16(blocks, 2)
    sc_raw = blocks[..., 4:16]  # 12 bytes: 8 6-bit scales + 8 6-bit mins
    qs = blocks[..., 16:144]  # 128 bytes → 256 nibbles

    # get_scale_min_k4 unpacking
    sc = np.empty(blocks.shape[:-1] + (8,), np.float32)
    mn = np.empty_like(sc)
    for j in range(8):
        if j < 4:
            sc[..., j] = (sc_raw[..., j] & 63).astype(np.float32)
            mn[..., j] = (sc_raw[..., j + 4] & 63).astype(np.float32)
        else:
            sc[..., j] = (
                (sc_raw[..., j + 4] & 0xF) | ((sc_raw[..., j - 4] >> 6) << 4)
            ).astype(np.float32)
            mn[..., j] = (
                (sc_raw[..., j + 4] >> 4) | ((sc_raw[..., j] >> 6) << 4)
            ).astype(np.float32)

    out = np.empty(blocks.shape[:-1] + (256,), np.float32)
    for pair in range(4):  # 64-element groups: sub-blocks (2p, 2p+1)
        grp = qs[..., 32 * pair:32 * (pair + 1)]
        lo = (grp & 0xF).astype(np.float32)
        hi = (grp >> 4).astype(np.float32)
        j0, j1 = 2 * pair, 2 * pair + 1
        out[..., 64 * pair:64 * pair + 32] = (
            d[..., None] * sc[..., j0:j0 + 1] * lo
            - dmin[..., None] * mn[..., j0:j0 + 1]
        )
        out[..., 64 * pair + 32:64 * pair + 64] = (
            d[..., None] * sc[..., j1:j1 + 1] * hi
            - dmin[..., None] * mn[..., j1:j1 + 1]
        )
    return out


def _deq_q6_k(blocks: np.ndarray) -> np.ndarray:
    ql = blocks[..., 0:128]
    qh = blocks[..., 128:192]
    scales = blocks[..., 192:208].copy().view(np.int8).astype(np.float32)
    d = _f16(blocks, 208)

    out = np.empty(blocks.shape[:-1] + (256,), np.float32)
    for half in range(2):  # 128-element halves
        l_ = ql[..., 64 * half:64 * half + 32]
        l2 = ql[..., 64 * half + 32:64 * half + 64]
        h = qh[..., 32 * half:32 * half + 32]
        q1 = ((l_ & 0xF) | ((h & 3) << 4)).astype(np.float32) - 32.0
        q2 = ((l2 & 0xF) | (((h >> 2) & 3) << 4)).astype(np.float32) - 32.0
        q3 = ((l_ >> 4) | (((h >> 4) & 3) << 4)).astype(np.float32) - 32.0
        q4 = ((l2 >> 4) | (((h >> 6) & 3) << 4)).astype(np.float32) - 32.0
        base = 128 * half
        out[..., base + 0:base + 32] = q1
        out[..., base + 32:base + 64] = q2
        out[..., base + 64:base + 96] = q3
        out[..., base + 96:base + 128] = q4
    sub = np.repeat(scales, 16, axis=-1)  # scale per 16 elements
    return out * sub * d[..., None]


def _deq_kquant_np(name: str) -> Callable[[np.ndarray], np.ndarray]:
    """k-quant numpy dequant via the jnp codec (host verification path —
    the hot path repacks blocks verbatim and dequantizes in-graph).
    jax imports stay inside deq() so parsing GGUF metadata never pulls in
    the accelerator runtime."""

    def deq(blocks: np.ndarray) -> np.ndarray:
        import jax

        from bigdl_tpu.quant import kquants

        fn = {"q2_k": kquants.dequant_q2_k, "q3_k": kquants.dequant_q3_k,
              "q5_k": kquants.dequant_q5_k}[name]
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            flat = np.asarray(fn(blocks[None]))[0]
        return flat.reshape(*blocks.shape[:-1], 256)

    return deq


def _deq_iq(name: str) -> Callable[[np.ndarray], np.ndarray]:
    def deq(blocks: np.ndarray) -> np.ndarray:
        from bigdl_tpu.quant import iq_quants

        if name == "iq1_m":
            raise NotImplementedError(
                "iq1_m: the scale-word layout is pending validation "
                "against a reference decoder; convert the checkpoint to "
                "iq1_s/iq2_xxs or a k-quant"
            )
        fn = {"iq2_xxs": iq_quants.dequant_iq2_xxs,
              "iq2_xs": iq_quants.dequant_iq2_xs,
              "iq1_s": iq_quants.dequant_iq1_s}[name]
        return fn(blocks)

    return deq


_DEQUANT: dict[int, Callable[[np.ndarray], np.ndarray]] = {
    GGML_Q4_0: _deq_q4_0, GGML_Q4_1: _deq_q4_1,
    GGML_Q5_0: _deq_q5_0, GGML_Q5_1: _deq_q5_1,
    GGML_Q8_0: _deq_q8_0, GGML_Q4_K: _deq_q4_k, GGML_Q6_K: _deq_q6_k,
    GGML_Q2_K: _deq_kquant_np("q2_k"), GGML_Q3_K: _deq_kquant_np("q3_k"),
    GGML_Q5_K: _deq_kquant_np("q5_k"),
    GGML_IQ2_XXS: _deq_iq("iq2_xxs"), GGML_IQ2_XS: _deq_iq("iq2_xs"),
    GGML_IQ1_S: _deq_iq("iq1_s"), GGML_IQ1_M: _deq_iq("iq1_m"),
}


# ---------------------------------------------------------------------------
# direct repack ggml block -> QTensor fields (no dequant round trip)
# ---------------------------------------------------------------------------

def _block_codes(qs: np.ndarray) -> np.ndarray:
    """ggml per-block nibbles (element j & j+16 in byte j of each 32-block)
    → element-order codes over the whole row: [..., nb, 16] → [..., nb*32]."""
    lo = qs & 0xF  # elements 0..15 of each block
    hi = qs >> 4  # elements 16..31
    codes = np.concatenate([lo, hi], axis=-1)  # [..., nb, 32] element order
    return codes.reshape(*codes.shape[:-2], -1)


def _pack_half_split(codes: np.ndarray) -> np.ndarray:
    """Row-wise half-split pack — must mirror quant/numerics.pack_nibbles:
    byte j = element j (lo) | element j + K/2 (hi)."""
    k = codes.shape[-1]
    return (codes[..., : k // 2] | (codes[..., k // 2:] << 4)).astype(np.uint8)


def _nibbles_to_ours(qs: np.ndarray) -> np.ndarray:
    """ggml nibble order → our half-split row layout (zero dequant)."""
    return _pack_half_split(_block_codes(qs))


def repack_to_qtensor(blocks: np.ndarray, ggml_type: int):
    """Returns (fields, our_qtype_name) for directly-mappable types —
    `fields` is a dict of QTensor array fields whose layouts match
    bigdl_tpu.quant.numerics exactly. Pure integer/f16-view repack, no
    dequantization round trip."""
    if ggml_type == GGML_Q4_0:
        d = _f16(blocks, 0).astype(np.float16)
        data = _nibbles_to_ours(blocks[..., 2:18])  # [..., K//2] row layout
        return dict(data=data, scales=d), "sym_int4"
    if ggml_type == GGML_Q4_1:
        d = _f16(blocks, 0).astype(np.float16)
        m = _f16(blocks, 2).astype(np.float16)
        data = _nibbles_to_ours(blocks[..., 4:20])
        return dict(data=data, scales=d, mins=m), "asym_int4"
    if ggml_type == GGML_Q8_0:
        d = _f16(blocks, 0).astype(np.float16)
        data = blocks[..., 2:34].copy().view(np.int8)
        return dict(
            data=data.reshape(*data.shape[:-2], -1), scales=d
        ), "sym_int8"
    if ggml_type == GGML_Q5_0:
        from bigdl_tpu.quant import kq_planar

        d = _f16(blocks, 0).astype(np.float16)
        h = _q5_high_bits(blocks, 2)
        qs = blocks[..., 6:22]
        codes = np.concatenate(
            [(qs & 0xF) | (h[..., :16] << 4), (qs >> 4) | (h[..., 16:] << 4)],
            axis=-1,
        ).astype(np.uint8)
        codes = codes.reshape(*codes.shape[:-2], -1)
        return dict(
            data=kq_planar.pack_planes_np(codes, (4, 1)), scales=d
        ), "sym_int5"
    if ggml_type == GGML_Q5_1:
        d = _f16(blocks, 0).astype(np.float16)
        m = _f16(blocks, 2).astype(np.float16)
        h = _q5_high_bits(blocks, 4)
        qs = blocks[..., 8:24]
        codes = np.concatenate(
            [(qs & 0xF) | (h[..., :16] << 4), (qs >> 4) | (h[..., 16:] << 4)],
            axis=-1,
        ).astype(np.int8)
        return dict(
            data=codes.reshape(*codes.shape[:-2], -1), scales=d, mins=m
        ), "asym_int5"
    if ggml_type in _KQUANT_TYPES:
        # planar repack (quant/kq_planar.py): codes + factored two-level
        # scales — the byte-exact TPU layout the fused GEMV kernel reads
        from bigdl_tpu.quant import kq_planar

        name = _KQUANT_TYPES[ggml_type]
        repack = getattr(kq_planar, f"from_{name.replace('_', '')}_blocks")
        return repack(blocks), name
    raise KeyError(ggml_type)


_REPACKABLE = {
    GGML_Q4_0, GGML_Q4_1, GGML_Q8_0, GGML_Q5_0, GGML_Q5_1,
    GGML_Q2_K, GGML_Q3_K, GGML_Q4_K, GGML_Q5_K, GGML_Q6_K,
}


# ---------------------------------------------------------------------------
# model assembly
# ---------------------------------------------------------------------------

def config_from_gguf(reader: GGUFReader) -> ModelConfig:
    md = reader.metadata
    arch = reader.architecture

    def g(key, default=None):
        return md.get(f"{arch}.{key}", default)

    heads = int(g("attention.head_count", 32))
    vocab = reader.tensors["token_embd.weight"].shape[0]
    kwargs: dict[str, Any] = dict(
        model_type={"qwen2": "qwen2", "mistral": "mistral"}.get(arch, "llama"),
        vocab_size=int(vocab),
        hidden_size=int(g("embedding_length", 4096)),
        intermediate_size=int(g("feed_forward_length", 11008)),
        num_hidden_layers=int(g("block_count", 32)),
        num_attention_heads=heads,
        num_key_value_heads=int(g("attention.head_count_kv", heads)),
        rms_norm_eps=float(g("attention.layer_norm_rms_epsilon", 1e-5)),
        rope_theta=float(g("rope.freq_base", 10000.0)),
        max_position_embeddings=int(g("context_length", 4096)),
        tie_word_embeddings="output.weight" not in reader.tensors,
    )
    # explicit head_dim (mistral-nemo style: head_dim != hidden/heads)
    key_len = g("attention.key_length")
    if key_len:
        kwargs["head_dim"] = int(key_len)
    # rope scaling metadata ({arch}.rope.scaling.*): linear / yarn
    sc_type = g("rope.scaling.type")
    sc_factor = g("rope.scaling.factor")
    if sc_type and sc_type != "none" and sc_factor:
        rs = {"rope_type": str(sc_type), "factor": float(sc_factor)}
        orig = g("rope.scaling.original_context_length")
        if orig:
            rs["original_max_position_embeddings"] = int(orig)
        kwargs["rope_scaling"] = rs
    # bias presence is detectable for ANY arch from the tensor directory
    # (qwen2 ships them; llama-arch exports of biased variants too)
    kwargs["attention_bias"] = "blk.0.attn_q.bias" in reader.tensors
    return ModelConfig(**kwargs)


def _unpermute_rows(n_heads: int):
    """Inverse of llama.cpp's HF→gguf row permute for Wq/Wk: gguf stores
    reshape(heads, d/2, 2, in).swap(1,2); invert back to HF order. Returns
    a row-index permutation (applies equally to packed data and scales)."""

    def perm(n_rows: int) -> np.ndarray:
        # forward permute: gguf[h, 2j + i] = hf[h, i*(d/2) + j]; the inverse
        # places value (h*d + 2j + i) at position (h, i, j)
        d = n_rows // n_heads
        idx = np.arange(n_rows).reshape(n_heads, d // 2, 2)
        return idx.transpose(0, 2, 1).reshape(-1)

    return perm


def load_gguf(
    path: str, qtype: Optional[str] = None, dtype=None
) -> tuple[ModelConfig, dict]:
    """Load a GGUF file into (ModelConfig, params) — the reference's
    `AutoModelForCausalLM.from_gguf` (transformers/model.py:391 →
    gguf/api.py load_gguf_model).

    qtype=None keeps each repackable tensor in its native ggml precision
    (mixed trees are fine: every leaf knows its own qtype); k-quant/float
    tensors are requantized to sym_int4 in that mode. An explicit qtype
    forces uniform requantization.
    """
    import jax.numpy as jnp

    from bigdl_tpu.quant import QTensor, quantize

    if dtype is None:
        dtype = jnp.bfloat16
    head_qtype = None
    if qtype is not None:
        from bigdl_tpu.quant.qtypes import split_mixed_qtype

        qtype, head_qtype = split_mixed_qtype(qtype)
    reader = GGUFReader(path)
    arch = reader.architecture
    if arch not in ("llama", "mistral", "qwen2"):
        raise NotImplementedError(
            f"gguf architecture {arch!r} (have llama/mistral/qwen2)"
        )
    config = config_from_gguf(reader)
    # llama.cpp's converter applies the rope row-permute only for
    # llama-architecture exports (LlamaModel.permute); qwen2 GGUFs are
    # stored in HF row order already.
    if arch in ("llama", "mistral"):
        perm_fn = _unpermute_rows(config.num_attention_heads)
        perm_fn_kv = _unpermute_rows(config.num_key_value_heads)
    else:
        perm_fn = perm_fn_kv = None

    def load_weight(name: str, permute=None, target_qtype=None):
        info = reader.tensors[name]
        if info.ggml_type in _REPACKABLE and qtype is None:
            blocks = reader.raw_blocks(name)
            fields, our_q = repack_to_qtensor(blocks, info.ggml_type)
            if permute is not None:
                p = permute(info.shape[0])
                fields = {k: v[p] for k, v in fields.items()}
            return QTensor(
                qtype=our_q,
                **{k: jnp.asarray(v) for k, v in fields.items()},
            )
        w = reader.dequantize(name)
        if permute is not None:
            w = w[permute(w.shape[0])]
        target = target_qtype or qtype or "sym_int4"
        return quantize(jnp.asarray(w, jnp.float32), target)

    def load_dense(name: str):
        return jnp.asarray(reader.dequantize(name)).astype(dtype)

    L = config.num_hidden_layers
    per_layer = []
    for i in range(L):
        p = f"blk.{i}."
        lt = {
            "attn_norm": load_dense(p + "attn_norm.weight"),
            "mlp_norm": load_dense(p + "ffn_norm.weight"),
            "wq": load_weight(p + "attn_q.weight", perm_fn),
            "wk": load_weight(p + "attn_k.weight", perm_fn_kv),
            "wv": load_weight(p + "attn_v.weight"),
            "wo": load_weight(p + "attn_output.weight"),
            "w_gate": load_weight(p + "ffn_gate.weight"),
            "w_up": load_weight(p + "ffn_up.weight"),
            "w_down": load_weight(p + "ffn_down.weight"),
        }
        if config.attention_bias:
            # biases would follow the same row permute as their weights,
            # but only llama-arch exports are permuted (and those have no
            # qkv bias) — load as stored.
            bq = reader.dequantize(p + "attn_q.bias")
            bk = reader.dequantize(p + "attn_k.bias")
            if perm_fn is not None:
                bq = bq[perm_fn(bq.shape[0])]
                bk = bk[perm_fn_kv(bk.shape[0])]
            lt["bq"] = jnp.asarray(bq).astype(dtype)
            lt["bk"] = jnp.asarray(bk).astype(dtype)
            lt["bv"] = load_dense(p + "attn_v.bias")
        per_layer.append(lt)

    from bigdl_tpu.convert.hf import _stack_qtensors

    def harmonize(vals):
        """llama.cpp mixes block types per layer (e.g. Q4_K_M quantizes
        some attn_v layers at q6_k); stacked scan leaves must share one
        qtype — requantize stragglers to the majority type."""
        qtypes = [v.qtype for v in vals]
        major = max(set(qtypes), key=qtypes.count)
        return [
            v if v.qtype == major
            else quantize(v.dequantize(jnp.float32), major)
            for v in vals
        ]

    layers = {}
    for k in per_layer[0]:
        vals = [d[k] for d in per_layer]
        if isinstance(vals[0], QTensor):
            layers[k] = _stack_qtensors(harmonize(vals))
        else:
            layers[k] = jnp.stack(vals)

    params: dict = {
        "layers": layers,
        "embed": load_dense("token_embd.weight"),
        "final_norm": load_dense("output_norm.weight"),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = load_weight("output.weight", target_qtype=head_qtype)
    return config, params
