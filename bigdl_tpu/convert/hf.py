"""HuggingFace checkpoint ingest.

Equivalent of the reference load path (`transformers/model.py:111`
`from_pretrained` → `load_convert` → `ggml_convert_low_bit`,
SURVEY.md §3.1), TPU-shaped: safetensors shards are streamed tensor by
tensor, each layer's weights are quantized immediately (peak host memory
~ one layer in fp32), and per-layer results are stacked along the leading
axis for `lax.scan`.

Shards are read via safetensors' torch framework (robust bf16/fp16
handling); torch is imported lazily and only by this ingest path —
the runtime itself never touches it.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant import QTensor, quantize
from bigdl_tpu.quant.qtypes import resolve_qtype

# our layer-param name -> HF per-layer suffix
_LAYER_MAP = {
    "attn_norm": "input_layernorm.weight",
    "mlp_norm": "post_attention_layernorm.weight",
    "wq": "self_attn.q_proj.weight",
    "wk": "self_attn.k_proj.weight",
    "wv": "self_attn.v_proj.weight",
    "wo": "self_attn.o_proj.weight",
    "w_gate": "mlp.gate_proj.weight",
    "w_up": "mlp.up_proj.weight",
    "w_down": "mlp.down_proj.weight",
    "bq": "self_attn.q_proj.bias",
    "bk": "self_attn.k_proj.bias",
    "bv": "self_attn.v_proj.bias",
}

_QUANT_TARGETS = {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"}


def state_dict_mapping(config: ModelConfig) -> dict[str, list[str]]:
    """our param path -> list of HF tensor names (one per layer for stacked)."""
    L = config.num_hidden_layers
    mapping: dict[str, list[str]] = {
        "embed": ["model.embed_tokens.weight"],
        "final_norm": ["model.norm.weight"],
    }
    if not config.tie_word_embeddings:
        mapping["lm_head"] = ["lm_head.weight"]
    for ours, suffix in _LAYER_MAP.items():
        if ours.startswith("b") and not config.attention_bias:
            continue
        mapping[f"layers.{ours}"] = [
            f"model.layers.{i}.{suffix}" for i in range(L)
        ]
    return mapping


def params_from_state_dict(
    config: ModelConfig,
    get_tensor: Callable[[str], np.ndarray],
    qtype: str = "sym_int4",
    dtype=jnp.bfloat16,
) -> dict:
    """Build the model param pytree from a tensor-name accessor.

    `get_tensor` returns a numpy array for an HF tensor name (backed by a
    dict for tests, or by lazy safetensors shards for real checkpoints).
    """
    spec = resolve_qtype(qtype)
    params: dict = {"layers": {}}

    def put(path: str, value):
        parts = path.split(".")
        node = params
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    for path, names in state_dict_mapping(config).items():
        leaf = path.split(".")[-1]
        quantize_it = (not spec.is_dense) and (
            leaf in _QUANT_TARGETS or path == "lm_head"
        )
        per_layer = []
        for name in names:
            arr = np.asarray(get_tensor(name))
            if quantize_it:
                per_layer.append(quantize(jnp.asarray(arr, jnp.float32), spec.name))
            else:
                per_layer.append(jnp.asarray(arr).astype(dtype))
        if len(per_layer) == 1:
            put(path, per_layer[0])
        elif isinstance(per_layer[0], QTensor):
            stacked = QTensor(
                data=jnp.stack([q.data for q in per_layer]),
                scales=jnp.stack([q.scales for q in per_layer]),
                mins=(
                    jnp.stack([q.mins for q in per_layer])
                    if per_layer[0].mins is not None
                    else None
                ),
                qtype=per_layer[0].qtype,
            )
            put(path, stacked)
        else:
            put(path, jnp.stack(per_layer))
    return params


def load_hf_checkpoint(
    model_path: str,
    qtype: str = "sym_int4",
    dtype=jnp.bfloat16,
    config: Optional[ModelConfig] = None,
) -> tuple[ModelConfig, dict]:
    """Load an HF-format local checkpoint directory (config.json +
    *.safetensors) into a quantized param tree."""
    import torch  # lazy: only the ingest path touches torch
    from safetensors import safe_open  # lazy: heavy import

    if config is None:
        with open(os.path.join(model_path, "config.json")) as f:
            config = ModelConfig.from_hf_config(json.load(f))

    index_path = os.path.join(model_path, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
    else:
        single = os.path.join(model_path, "model.safetensors")
        with safe_open(single, framework="pt") as f:
            weight_map = {k: "model.safetensors" for k in f.keys()}

    handles: dict[str, object] = {}

    def get_tensor(name: str) -> np.ndarray:
        if name not in weight_map and name == "lm_head.weight":
            # some checkpoints tie without the flag; fall back to embeddings
            name = "model.embed_tokens.weight"
        shard = weight_map[name]
        if shard not in handles:
            # torch framework: robust bf16/fp16 handling without ml_dtypes
            handles[shard] = safe_open(
                os.path.join(model_path, shard), framework="pt"
            )
        t = handles[shard].get_tensor(name)
        return t.to(dtype=torch.float32).numpy()

    params = params_from_state_dict(config, get_tensor, qtype, dtype)
    return config, params
