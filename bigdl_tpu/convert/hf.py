"""HuggingFace checkpoint ingest.

Equivalent of the reference load path (`transformers/model.py:111`
`from_pretrained` → `load_convert` → `ggml_convert_low_bit`,
SURVEY.md §3.1) plus the weight-level prep `_optimize_pre` does per
architecture (convert.py:886-1076: qkv merges/splits, NormHead→Linear,
fused gate_up handling), TPU-shaped: safetensors shards are streamed
tensor by tensor, each layer's weights are quantized immediately (peak
host memory ~ one layer in fp32), and per-layer results are stacked
along the leading axis for `lax.scan`.

Per-model_type weight translation lives in the `_FAMILY_*` tables below —
the weights-side counterpart of the config translation in
bigdl_tpu/models/config.py. Where the reference merges separate q/k/v
into one fused linear for kernel efficiency (merge_qkv,
models/common.py:22-53), we keep q/k/v separate (XLA fuses the three
matmuls reading one activation), and instead *split* checkpoints that
ship fused (phi3 qkv_proj/gate_up_proj, baichuan W_pack, internlm2 wqkv).

Shards are read via safetensors' torch framework (robust bf16/fp16
handling); torch is imported lazily and only by this ingest path —
the runtime itself never touches it.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant import QTensor, quantize
from bigdl_tpu.quant.qtypes import resolve_qtype

_QUANT_TARGETS = {
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
    "wqkv",  # pre-fused checkpoints ingested fused (baichuan_m1 W_pack)
    "w_gate_e", "w_up_e", "w_down_e", "w_gate_s", "w_up_s", "w_down_s",
    # rwkv projections (models/rwkv.py)
    "att_k", "att_v", "att_r", "att_g", "att_o", "ffn_k", "ffn_r", "ffn_v",
    # MLA projections (models/deepseek.py; the per-head w_uk/w_uv factors
    # stay dense — they are absorbed into f32 attention math)
    "w_dq", "w_uq", "w_dkv",
}

Get = Callable[[str], np.ndarray]


# ---------------------------------------------------------------------------
# per-family layer/top tensor builders
# ---------------------------------------------------------------------------

def _llama_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    p = f"model.layers.{i}."
    out = {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "w_gate": get(p + "mlp.gate_proj.weight"),
        "w_up": get(p + "mlp.up_proj.weight"),
        "w_down": get(p + "mlp.down_proj.weight"),
    }
    if config.attention_bias:
        out["bq"] = get(p + "self_attn.q_proj.bias")
        out["bk"] = get(p + "self_attn.k_proj.bias")
        out["bv"] = get(p + "self_attn.v_proj.bias")
    if config.attention_out_bias:
        out["bo"] = get(p + "self_attn.o_proj.bias")
    if config.norm_bias:
        out["attn_norm_b"] = get(p + "input_layernorm.bias")
        out["mlp_norm_b"] = get(p + "post_attention_layernorm.bias")
    return out


def _llama_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.norm.weight"),
    }
    if config.norm_bias:
        out["final_norm_b"] = get("model.norm.bias")
    if not config.tie_word_embeddings:
        out["lm_head"] = get("lm_head.weight")
    return out


def _gemma2_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    p = f"model.layers.{i}."
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "post_attn_norm": get(p + "post_attention_layernorm.weight"),
        "mlp_norm": get(p + "pre_feedforward_layernorm.weight"),
        "post_mlp_norm": get(p + "post_feedforward_layernorm.weight"),
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "w_gate": get(p + "mlp.gate_proj.weight"),
        "w_up": get(p + "mlp.up_proj.weight"),
        "w_down": get(p + "mlp.down_proj.weight"),
    }


def _gemma3_get(get: Get) -> Get:
    """Multimodal gemma3 checkpoints (4B+) keep text weights under
    `model.language_model.` (HF >= 4.52) or `language_model.model.`
    (original releases); gemma3_text (1B) uses bare `model.` names."""

    def g(name):
        try:
            return get(name)
        except KeyError:
            pass
        try:
            return get("model.language_" + name)
        except KeyError:
            return get("language_model." + name)

    return g


def _gemma3_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """gemma2 norm quartet + per-head q/k RMSNorm."""
    g = _gemma3_get(get)
    out = _gemma2_layer(config, i, g)
    p = f"model.layers.{i}."
    out["q_norm"] = g(p + "self_attn.q_norm.weight")
    out["k_norm"] = g(p + "self_attn.k_norm.weight")
    return out


def _gemma3_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return _llama_top(config, _gemma3_get(get))


def _phi3_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """phi3 ships fused qkv_proj [QD+2*KD, H] and gate_up_proj [2I, H]
    (reference models/phi3.py attention path); split for our layout."""
    p = f"model.layers.{i}."
    qkv = get(p + "self_attn.qkv_proj.weight")
    QD, KD = config.q_dim, config.kv_dim
    gate_up = get(p + "mlp.gate_up_proj.weight")
    I = gate_up.shape[0] // 2
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": qkv[:QD],
        "wk": qkv[QD:QD + KD],
        "wv": qkv[QD + KD:],
        "wo": get(p + "self_attn.o_proj.weight"),
        "w_gate": gate_up[:I],
        "w_up": gate_up[I:],
        "w_down": get(p + "mlp.down_proj.weight"),
    }


def _baichuan_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """baichuan W_pack [3*H, H] fused qkv (reference models/baichuan.py
    pre-optimization splits it the same way)."""
    p = f"model.layers.{i}."
    pack = get(p + "self_attn.W_pack.weight")
    H = config.hidden_size
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": pack[:H],
        "wk": pack[H:2 * H],
        "wv": pack[2 * H:],
        "wo": get(p + "self_attn.o_proj.weight"),
        "w_gate": get(p + "mlp.gate_proj.weight"),
        "w_up": get(p + "mlp.up_proj.weight"),
        "w_down": get(p + "mlp.down_proj.weight"),
    }


def _baichuan_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.norm.weight"),
    }
    if not config.tie_word_embeddings:
        # NormHead: lm-head rows are L2-normalized at inference; the
        # reference converts NormHead→Linear with normalized weights
        # (convert.py:886 _optimize_pre); we bake it in at ingest.
        w = get("lm_head.weight").astype(np.float32)
        norms = np.linalg.norm(w, axis=1, keepdims=True)
        out["lm_head"] = w / np.maximum(norms, 1e-12)
    return out


def _internlm2_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """internlm2 grouped wqkv [(Hkv*(g+2))*D, H]: per kv group g q-heads
    then one k and one v head."""
    p = f"model.layers.{i}."
    D = config.head_dim_
    Hkv = config.num_key_value_heads
    g = config.num_attention_heads // Hkv
    wqkv = get(p + "attention.wqkv.weight")
    H = wqkv.shape[-1]
    grouped = wqkv.reshape(Hkv, g + 2, D, H)
    return {
        "attn_norm": get(p + "attention_norm.weight"),
        "mlp_norm": get(p + "ffn_norm.weight"),
        "wq": grouped[:, :g].reshape(Hkv * g * D, H),
        "wk": grouped[:, g].reshape(Hkv * D, H),
        "wv": grouped[:, g + 1].reshape(Hkv * D, H),
        "wo": get(p + "attention.wo.weight"),
        "w_gate": get(p + "feed_forward.w1.weight"),
        "w_up": get(p + "feed_forward.w3.weight"),
        "w_down": get(p + "feed_forward.w2.weight"),
    }


def _internlm2_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("model.tok_embeddings.weight"),
        "final_norm": get("model.norm.weight"),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = get("output.weight")
    return out


def _starcoder2_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    p = f"model.layers.{i}."
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "attn_norm_b": get(p + "input_layernorm.bias"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "mlp_norm_b": get(p + "post_attention_layernorm.bias"),
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "bq": get(p + "self_attn.q_proj.bias"),
        "bk": get(p + "self_attn.k_proj.bias"),
        "bv": get(p + "self_attn.v_proj.bias"),
        "bo": get(p + "self_attn.o_proj.bias"),
        "w_up": get(p + "mlp.c_fc.weight"),
        "b_up": get(p + "mlp.c_fc.bias"),
        "w_down": get(p + "mlp.c_proj.weight"),
        "b_down": get(p + "mlp.c_proj.bias"),
    }


def _glm_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """HF 'glm' (glm-4 family): separate q/k/v with bias, fused gate_up."""
    p = f"model.layers.{i}."
    gate_up = get(p + "mlp.gate_up_proj.weight")
    I = gate_up.shape[0] // 2
    out = {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "w_gate": gate_up[:I],
        "w_up": gate_up[I:],
        "w_down": get(p + "mlp.down_proj.weight"),
    }
    if config.attention_bias:
        out["bq"] = get(p + "self_attn.q_proj.bias")
        out["bk"] = get(p + "self_attn.k_proj.bias")
        out["bv"] = get(p + "self_attn.v_proj.bias")
    return out


def _chatglm_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """THUDM chatglm2/3 + glm-4 layout: fused query_key_value
    [QD+2*KD, H] (+bias) and swiglu dense_h_to_4h [2I, H] (reference
    models/chatglm2.py:229 reads the fused qkv; split_mlp in
    convert.py:1048-1055 splits the MLP the same way)."""
    p = f"transformer.encoder.layers.{i}."
    qkv = get(p + "self_attention.query_key_value.weight")
    QD, KD = config.q_dim, config.kv_dim
    h4h = get(p + "mlp.dense_h_to_4h.weight")
    I = h4h.shape[0] // 2
    out = {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": qkv[:QD],
        "wk": qkv[QD:QD + KD],
        "wv": qkv[QD + KD:],
        "wo": get(p + "self_attention.dense.weight"),
        "w_gate": h4h[:I],  # swiglu: silu(chunk0) * chunk1
        "w_up": h4h[I:],
        "w_down": get(p + "mlp.dense_4h_to_h.weight"),
    }
    if config.attention_bias:
        b = get(p + "self_attention.query_key_value.bias")
        out["bq"], out["bk"], out["bv"] = b[:QD], b[QD:QD + KD], b[QD + KD:]
    return out


def _chatglm_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("transformer.embedding.word_embeddings.weight"),
        "final_norm": get("transformer.encoder.final_layernorm.weight"),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = get("transformer.output_layer.weight")
    return out


def _qwen2_vl_get(get: Get):
    """Qwen2-VL text keys moved across transformers versions:
    `model.layers.*` (original checkpoints) vs `model.language_model.
    layers.*` (HF >= 4.52 refactor). Try both."""

    def g(name: str):
        try:
            return get(name.replace("model.", "model.language_model.", 1))
        except KeyError:
            return get(name)

    return g


def _qwen2_vl_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    return _llama_layer(config, i, _qwen2_vl_get(get))


def _qwen2_vl_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    g = _qwen2_vl_get(get)

    def top_get(name: str):
        if name == "model.embed_tokens.weight":
            return g(name)
        if name == "model.norm.weight":
            return g(name)
        return get(name)  # lm_head.weight stays top-level

    return _llama_top(config, top_get)


def _mpt_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """MPT: fused Wqkv [3H, H], bias-free layernorms, non-gated gelu MLP
    (reference models/mpt.py splits the same fused attention)."""
    p = f"transformer.blocks.{i}."
    H = config.hidden_size
    wqkv = get(p + "attn.Wqkv.weight")
    return {
        "attn_norm": get(p + "norm_1.weight"),
        "mlp_norm": get(p + "norm_2.weight"),
        "wq": wqkv[:H],
        "wk": wqkv[H:2 * H],
        "wv": wqkv[2 * H:],
        "wo": get(p + "attn.out_proj.weight"),
        "w_up": get(p + "ffn.up_proj.weight"),
        "w_down": get(p + "ffn.down_proj.weight"),
    }


def _mpt_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return {
        "embed": get("transformer.wte.weight"),
        "final_norm": get("transformer.norm_f.weight"),
    }  # head tied to wte


def _gpt2_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """GPT-2 stores linears as Conv1D ([in, out] — transposed) with a fused
    c_attn [in, 3H]."""
    p = f"transformer.h.{i}."
    H = config.hidden_size
    c_attn = get(p + "attn.c_attn.weight").T  # [3H, H]
    b_attn = get(p + "attn.c_attn.bias")
    return {
        "attn_norm": get(p + "ln_1.weight"),
        "attn_norm_b": get(p + "ln_1.bias"),
        "mlp_norm": get(p + "ln_2.weight"),
        "mlp_norm_b": get(p + "ln_2.bias"),
        "wq": c_attn[:H], "wk": c_attn[H:2 * H], "wv": c_attn[2 * H:],
        "bq": b_attn[:H], "bk": b_attn[H:2 * H], "bv": b_attn[2 * H:],
        "wo": get(p + "attn.c_proj.weight").T,
        "bo": get(p + "attn.c_proj.bias"),
        "w_up": get(p + "mlp.c_fc.weight").T,
        "b_up": get(p + "mlp.c_fc.bias"),
        "w_down": get(p + "mlp.c_proj.weight").T,
        "b_down": get(p + "mlp.c_proj.bias"),
    }


def _gpt2_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return {
        "embed": get("transformer.wte.weight"),
        "wpe": get("transformer.wpe.weight"),
        "final_norm": get("transformer.ln_f.weight"),
        "final_norm_b": get("transformer.ln_f.bias"),
    }


def _split_headwise_qkv(fused: np.ndarray, n_heads: int, head_dim: int):
    """[heads*3*D, H] fused per head (bloom/gptneox query_key_value) →
    (q, k, v) each [heads*D, H]."""
    H_in = fused.shape[-1]
    g = fused.reshape(n_heads, 3, head_dim, H_in)
    return (
        g[:, 0].reshape(-1, H_in),
        g[:, 1].reshape(-1, H_in),
        g[:, 2].reshape(-1, H_in),
    )


def _bloom_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    p = f"transformer.h.{i}."
    D = config.head_dim_
    nh = config.num_attention_heads
    wq, wk, wv = _split_headwise_qkv(
        get(p + "self_attention.query_key_value.weight"), nh, D
    )
    bq, bk, bv = (
        b.reshape(-1)
        for b in _split_headwise_qkv(
            get(p + "self_attention.query_key_value.bias").reshape(-1, 1), nh, D
        )
    )
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "attn_norm_b": get(p + "input_layernorm.bias"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "mlp_norm_b": get(p + "post_attention_layernorm.bias"),
        "wq": wq, "wk": wk, "wv": wv, "bq": bq, "bk": bk, "bv": bv,
        "wo": get(p + "self_attention.dense.weight"),
        "bo": get(p + "self_attention.dense.bias"),
        "w_up": get(p + "mlp.dense_h_to_4h.weight"),
        "b_up": get(p + "mlp.dense_h_to_4h.bias"),
        "w_down": get(p + "mlp.dense_4h_to_h.weight"),
        "b_down": get(p + "mlp.dense_4h_to_h.bias"),
    }


def _bloom_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return {
        "embed": get("transformer.word_embeddings.weight"),
        "embed_norm": get("transformer.word_embeddings_layernorm.weight"),
        "embed_norm_b": get("transformer.word_embeddings_layernorm.bias"),
        "final_norm": get("transformer.ln_f.weight"),
        "final_norm_b": get("transformer.ln_f.bias"),
    }


def _gptneox_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    p = f"gpt_neox.layers.{i}."
    D = config.head_dim_
    nh = config.num_attention_heads
    wq, wk, wv = _split_headwise_qkv(
        get(p + "attention.query_key_value.weight"), nh, D
    )
    bq, bk, bv = (
        b.reshape(-1)
        for b in _split_headwise_qkv(
            get(p + "attention.query_key_value.bias").reshape(-1, 1), nh, D
        )
    )
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "attn_norm_b": get(p + "input_layernorm.bias"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "mlp_norm_b": get(p + "post_attention_layernorm.bias"),
        "wq": wq, "wk": wk, "wv": wv, "bq": bq, "bk": bk, "bv": bv,
        "wo": get(p + "attention.dense.weight"),
        "bo": get(p + "attention.dense.bias"),
        "w_up": get(p + "mlp.dense_h_to_4h.weight"),
        "b_up": get(p + "mlp.dense_h_to_4h.bias"),
        "w_down": get(p + "mlp.dense_4h_to_h.weight"),
        "b_down": get(p + "mlp.dense_4h_to_h.bias"),
    }


def _gptneox_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("gpt_neox.embed_in.weight"),
        "final_norm": get("gpt_neox.final_layer_norm.weight"),
        "final_norm_b": get("gpt_neox.final_layer_norm.bias"),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = get("embed_out.weight")
    return out


def _mixtral_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    p = f"model.layers.{i}."
    E = config.num_experts
    out = {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "router": get(p + "block_sparse_moe.gate.weight"),
        "w_gate_e": np.stack(
            [get(p + f"block_sparse_moe.experts.{e}.w1.weight") for e in range(E)]
        ),
        "w_up_e": np.stack(
            [get(p + f"block_sparse_moe.experts.{e}.w3.weight") for e in range(E)]
        ),
        "w_down_e": np.stack(
            [get(p + f"block_sparse_moe.experts.{e}.w2.weight") for e in range(E)]
        ),
    }
    return out


def _qwen2_moe_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    p = f"model.layers.{i}."
    E = config.num_experts
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "bq": get(p + "self_attn.q_proj.bias"),
        "bk": get(p + "self_attn.k_proj.bias"),
        "bv": get(p + "self_attn.v_proj.bias"),
        "router": get(p + "mlp.gate.weight"),
        "w_gate_e": np.stack(
            [get(p + f"mlp.experts.{e}.gate_proj.weight") for e in range(E)]
        ),
        "w_up_e": np.stack(
            [get(p + f"mlp.experts.{e}.up_proj.weight") for e in range(E)]
        ),
        "w_down_e": np.stack(
            [get(p + f"mlp.experts.{e}.down_proj.weight") for e in range(E)]
        ),
        "w_gate_s": get(p + "mlp.shared_expert.gate_proj.weight"),
        "w_up_s": get(p + "mlp.shared_expert.up_proj.weight"),
        "w_down_s": get(p + "mlp.shared_expert.down_proj.weight"),
        "shared_gate": get(p + "mlp.shared_expert_gate.weight"),
    }


def _prefixed(get: Get, prefix: str) -> Get:
    def g(name):
        return get(prefix + name)
    return g


def _internvl_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """InternVL (HF-converted): standard qwen2/llama decoder under the
    `model.language_model.` prefix (vision tower + projector load
    separately via models/internvl.py)."""
    try:
        return _llama_layer(config, i, _prefixed(get, "model.language_"))
    except KeyError:  # older conversions: language_model.model.layers...
        return _llama_layer(config, i, _prefixed(get, "language_model."))


def _internvl_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    try:
        out = {
            "embed": get("model.language_model.embed_tokens.weight"),
            "final_norm": get("model.language_model.norm.weight"),
        }
        head_name = "lm_head.weight"
    except KeyError:
        out = {
            "embed": get("language_model.model.embed_tokens.weight"),
            "final_norm": get("language_model.model.norm.weight"),
        }
        head_name = "language_model.lm_head.weight"
    if not config.tie_word_embeddings:
        out["lm_head"] = get(head_name)
    return out


def _janus_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Janus: llama decoder under `model.language_model.` (HF layout;
    vision tower + aligner load separately via models/janus.py)."""
    return _llama_layer(config, i, _prefixed(get, "model.language_"))


def _janus_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("model.language_model.embed_tokens.weight"),
        "final_norm": get("model.language_model.norm.weight"),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = get("lm_head.weight")
    return out


def _minicpmv_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """MiniCPM-V stores its language model under the `llm.` prefix
    (OpenBMB MiniCPMV: self.llm = Qwen2/Llama ForCausalLM); layer layout
    is plain llama/qwen2. Vision tower (`vpm.`) and resampler weights
    load separately via models/minicpmv.py."""
    return _llama_layer(config, i, _prefixed(get, "llm."))


def _minicpmv_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return _llama_top(config, _prefixed(get, "llm."))


def _qwen2_audio_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Qwen2-Audio stores its qwen2 decoder under `language_model.`
    (transformers Qwen2AudioForConditionalGeneration); audio tower and
    projector load separately via models/qwen2_audio.py."""
    return _llama_layer(config, i, _prefixed(get, "language_model."))


def _qwen2_audio_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return _llama_top(config, _prefixed(get, "language_model."))


def _yuan_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Yuan-2 (yuan_hf_model.py layout): llama names + the LFA filter's
    two Conv2d(k=(2,1)) stages, each split into its two time taps
    ([O, C, 2, 1] -> Wa = [..., 0, 0], Wb = [..., 1, 0]) so the filter
    runs as shift+matmul (models/yuan.py lfa_filter)."""
    p = f"model.layers.{i}."
    c1 = get(p + "self_attn.lf_gate.conv1.weight")  # [C/2, C, 2, 1]
    c2 = get(p + "self_attn.lf_gate.conv2.weight")  # [C, C/2, 2, 1]
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "w_gate": get(p + "mlp.gate_proj.weight"),
        "w_up": get(p + "mlp.up_proj.weight"),
        "w_down": get(p + "mlp.down_proj.weight"),
        "lf_w1a": c1[:, :, 0, 0], "lf_w1b": c1[:, :, 1, 0],
        "lf_b1": get(p + "self_attn.lf_gate.conv1.bias"),
        "lf_w2a": c2[:, :, 0, 0], "lf_w2b": c2[:, :, 1, 0],
        "lf_b2": get(p + "self_attn.lf_gate.conv2.bias"),
        "lf_norm": get(p + "self_attn.lf_gate.output_layernorm.weight"),
    }


def _qwen3_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Qwen3: llama names + per-head q/k RMSNorm weights."""
    out = _llama_layer(config, i, get)
    p = f"model.layers.{i}."
    out["q_norm"] = get(p + "self_attn.q_norm.weight")
    out["k_norm"] = get(p + "self_attn.k_norm.weight")
    return out


def _qwen3_moe_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    p = f"model.layers.{i}."
    E = config.num_experts
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "q_norm": get(p + "self_attn.q_norm.weight"),
        "k_norm": get(p + "self_attn.k_norm.weight"),
        "router": get(p + "mlp.gate.weight"),
        "w_gate_e": np.stack(
            [get(p + f"mlp.experts.{e}.gate_proj.weight") for e in range(E)]
        ),
        "w_up_e": np.stack(
            [get(p + f"mlp.experts.{e}.up_proj.weight") for e in range(E)]
        ),
        "w_down_e": np.stack(
            [get(p + f"mlp.experts.{e}.down_proj.weight") for e in range(E)]
        ),
    }


def _phi_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Phi-1/2: parallel attn+mlp read the SAME input layernorm — it
    loads into both attn_norm and mlp_norm slots (falcon-7b pattern);
    fc1/fc2 MLP and `self_attn.dense` output, all biased."""
    p = f"model.layers.{i}."
    ln_w = get(p + "input_layernorm.weight")
    ln_b = get(p + "input_layernorm.bias")
    return {
        "attn_norm": ln_w, "attn_norm_b": ln_b,
        "mlp_norm": ln_w, "mlp_norm_b": ln_b,
        "wq": get(p + "self_attn.q_proj.weight"),
        "bq": get(p + "self_attn.q_proj.bias"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "bk": get(p + "self_attn.k_proj.bias"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "bv": get(p + "self_attn.v_proj.bias"),
        "wo": get(p + "self_attn.dense.weight"),
        "bo": get(p + "self_attn.dense.bias"),
        "w_up": get(p + "mlp.fc1.weight"),
        "b_up": get(p + "mlp.fc1.bias"),
        "w_down": get(p + "mlp.fc2.weight"),
        "b_down": get(p + "mlp.fc2.bias"),
    }


def _phi_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.final_layernorm.weight"),
        "final_norm_b": get("model.final_layernorm.bias"),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = get("lm_head.weight")
        out["lm_head_b"] = get("lm_head.bias")
    return out


def _cohere_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Cohere: one shared bias-free LayerNorm feeds both parallel
    branches."""
    p = f"model.layers.{i}."
    ln = get(p + "input_layernorm.weight")
    out = {
        "attn_norm": ln, "mlp_norm": ln,
        "wq": get(p + "self_attn.q_proj.weight"),
        "wk": get(p + "self_attn.k_proj.weight"),
        "wv": get(p + "self_attn.v_proj.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "w_gate": get(p + "mlp.gate_proj.weight"),
        "w_up": get(p + "mlp.up_proj.weight"),
        "w_down": get(p + "mlp.down_proj.weight"),
    }
    if config.attention_bias:
        out["bq"] = get(p + "self_attn.q_proj.bias")
        out["bk"] = get(p + "self_attn.k_proj.bias")
        out["bv"] = get(p + "self_attn.v_proj.bias")
    return out


def _falcon_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Falcon fused query_key_value is grouped per kv-head
    ([q0..q_{g-1}, k, v] x num_kv, HF FalconAttention._split_heads):
    ungroup to separate q/k/v. falcon-7b (parallel_attn, single
    input_layernorm) duplicates that norm into attn_norm/mlp_norm —
    exactly equivalent since both branches read the same normed input."""
    p = f"transformer.h.{i}."
    Hq, Hkv, D = (config.num_attention_heads, config.num_key_value_heads,
                  config.head_dim_)
    qkv = get(p + "self_attention.query_key_value.weight")
    g = Hq // Hkv
    grouped = qkv.reshape(Hkv, g + 2, D, -1)
    wq = grouped[:, :g].reshape(Hq * D, -1)
    wk = grouped[:, g].reshape(Hkv * D, -1)
    wv = grouped[:, g + 1].reshape(Hkv * D, -1)
    out = {
        "wq": wq, "wk": wk, "wv": wv,
        "wo": get(p + "self_attention.dense.weight"),
        "w_up": get(p + "mlp.dense_h_to_4h.weight"),
        "w_down": get(p + "mlp.dense_4h_to_h.weight"),
    }
    if config.attention_bias:
        bqkv = get(p + "self_attention.query_key_value.bias")
        bg = bqkv.reshape(Hkv, g + 2, D)
        out["bq"] = bg[:, :g].reshape(Hq * D)
        out["bk"] = bg[:, g].reshape(Hkv * D)
        out["bv"] = bg[:, g + 1].reshape(Hkv * D)
    if config.attention_out_bias:
        out["bo"] = get(p + "self_attention.dense.bias")
    if config.mlp_bias:
        out["b_up"] = get(p + "mlp.dense_h_to_4h.bias")
        out["b_down"] = get(p + "mlp.dense_4h_to_h.bias")
    try:  # new_decoder_architecture: separate ln_attn / ln_mlp
        out["attn_norm"] = get(p + "ln_attn.weight")
        out["attn_norm_b"] = get(p + "ln_attn.bias")
        out["mlp_norm"] = get(p + "ln_mlp.weight")
        out["mlp_norm_b"] = get(p + "ln_mlp.bias")
    except KeyError:
        out["attn_norm"] = get(p + "input_layernorm.weight")
        out["attn_norm_b"] = get(p + "input_layernorm.bias")
        if config.parallel_residual:  # falcon-7b: one shared norm
            out["mlp_norm"] = out["attn_norm"]
            out["mlp_norm_b"] = out["attn_norm_b"]
        else:  # falcon-rw sequential layout
            out["mlp_norm"] = get(p + "post_attention_layernorm.weight")
            out["mlp_norm_b"] = get(p + "post_attention_layernorm.bias")
    return out


def _falcon_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("transformer.word_embeddings.weight"),
        "final_norm": get("transformer.ln_f.weight"),
        "final_norm_b": get("transformer.ln_f.bias"),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = get("lm_head.weight")
    return out


def _rwkv_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """RWKV v4/v5 HF layout (transformers modeling_rwkv.py for v4; the
    rwkv-5-world remote-code schema adds gate + ln_x; reference
    models/rwkv4.py / rwkv5.py). time_mix_* ship [1,1,C] — squeezed to
    [C]; v5 time_decay/time_first reshape to [H, D]."""
    p = f"rwkv.blocks.{i}."
    v5 = config.rwkv_head_size is not None

    def vec(name):
        return np.asarray(get(name)).reshape(-1)

    out = {
        "ln1_w": get(p + "ln1.weight"), "ln1_b": get(p + "ln1.bias"),
        "ln2_w": get(p + "ln2.weight"), "ln2_b": get(p + "ln2.bias"),
        "att_mix_k": vec(p + "attention.time_mix_key"),
        "att_mix_v": vec(p + "attention.time_mix_value"),
        "att_mix_r": vec(p + "attention.time_mix_receptance"),
        "att_k": get(p + "attention.key.weight"),
        "att_v": get(p + "attention.value.weight"),
        "att_r": get(p + "attention.receptance.weight"),
        "att_o": get(p + "attention.output.weight"),
        "ffn_mix_k": vec(p + "feed_forward.time_mix_key"),
        "ffn_mix_r": vec(p + "feed_forward.time_mix_receptance"),
        "ffn_k": get(p + "feed_forward.key.weight"),
        "ffn_r": get(p + "feed_forward.receptance.weight"),
        "ffn_v": get(p + "feed_forward.value.weight"),
    }
    if v5:
        H = config.num_attention_heads
        D = config.rwkv_head_size
        out["att_decay"] = vec(p + "attention.time_decay").reshape(H, D)
        out["att_first"] = vec(p + "attention.time_first").reshape(H, D)
        out["att_mix_g"] = vec(p + "attention.time_mix_gate")
        out["att_g"] = get(p + "attention.gate.weight")
        out["ln_x_w"] = get(p + "attention.ln_x.weight")
        out["ln_x_b"] = get(p + "attention.ln_x.bias")
    else:
        out["att_decay"] = vec(p + "attention.time_decay")
        out["att_first"] = vec(p + "attention.time_first")
    return out


def _rwkv_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return {
        "embed": get("rwkv.embeddings.weight"),
        "ln0_w": get("rwkv.blocks.0.pre_ln.weight"),
        "ln0_b": get("rwkv.blocks.0.pre_ln.bias"),
        "final_norm": get("rwkv.ln_out.weight"),
        "final_norm_b": get("rwkv.ln_out.bias"),
        "lm_head": get("head.weight"),
    }


def _qwen_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Qwen v1 (Qwen-7B remote code; reference models/qwen.py): fused
    biased c_attn [3H, H], bias-free c_proj, and an MLP computed as
    c_proj(w1(x) * silu(w2(x))) — w2 is the gate, w1 the up."""
    p = f"transformer.h.{i}."
    H = config.hidden_size
    c_attn = get(p + "attn.c_attn.weight")  # [3H, H] (nn.Linear rows)
    b_attn = get(p + "attn.c_attn.bias")
    return {
        "attn_norm": get(p + "ln_1.weight"),
        "mlp_norm": get(p + "ln_2.weight"),
        "wq": c_attn[:H], "wk": c_attn[H:2 * H], "wv": c_attn[2 * H:],
        "bq": b_attn[:H], "bk": b_attn[H:2 * H], "bv": b_attn[2 * H:],
        "wo": get(p + "attn.c_proj.weight"),
        "w_gate": get(p + "mlp.w2.weight"),
        "w_up": get(p + "mlp.w1.weight"),
        "w_down": get(p + "mlp.c_proj.weight"),
    }


def _qwen_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return {
        "embed": get("transformer.wte.weight"),
        "final_norm": get("transformer.ln_f.weight"),
        "lm_head": get("lm_head.weight"),
    }


def _deci_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """DeciLM: llama leaf names but VARIABLE GQA — each layer ships its
    own kv head count. Scan-stacked layers need uniform shapes, so k/v
    projections replicate head blocks up to the global max: exact,
    because attention with kv head j repeated r times equals GQA mapping
    q-head h -> head h // (Hq/Hkv_layer) (repeat_kv commutes with the
    grouping)."""
    out = _llama_layer(config, i, get)
    D = config.head_dim_
    target = config.num_key_value_heads * D
    for name in ("wk", "wv"):
        w = out[name]
        if w.shape[0] != target:
            hkv_l = w.shape[0] // D
            reps = target // w.shape[0]
            assert reps * w.shape[0] == target, (
                f"layer {i}: kv heads {hkv_l} do not divide the max "
                f"{config.num_key_value_heads}"
            )
            out[name] = np.repeat(
                w.reshape(hkv_l, D, -1), reps, axis=0
            ).reshape(target, -1)
    return out


def _gptbigcode_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """GPT-BigCode (starcoder v1): gpt2 naming but nn.Linear weights
    (no Conv1D transpose) and multi-query attention — the fused c_attn
    stacks [H query rows | head_dim k rows | head_dim v rows]."""
    p = f"transformer.h.{i}."
    H = config.hidden_size
    KD = config.num_key_value_heads * config.head_dim_
    c_attn = get(p + "attn.c_attn.weight")  # [H + 2*KD, H]
    b_attn = get(p + "attn.c_attn.bias")
    return {
        "attn_norm": get(p + "ln_1.weight"),
        "attn_norm_b": get(p + "ln_1.bias"),
        "mlp_norm": get(p + "ln_2.weight"),
        "mlp_norm_b": get(p + "ln_2.bias"),
        "wq": c_attn[:H], "wk": c_attn[H:H + KD], "wv": c_attn[H + KD:],
        "bq": b_attn[:H], "bk": b_attn[H:H + KD], "bv": b_attn[H + KD:],
        "wo": get(p + "attn.c_proj.weight"),
        "bo": get(p + "attn.c_proj.bias"),
        "w_up": get(p + "mlp.c_fc.weight"),
        "b_up": get(p + "mlp.c_fc.bias"),
        "w_down": get(p + "mlp.c_proj.weight"),
        "b_down": get(p + "mlp.c_proj.bias"),
    }


def _gptbigcode_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    out = {
        "embed": get("transformer.wte.weight"),
        "wpe": get("transformer.wpe.weight"),
        "final_norm": get("transformer.ln_f.weight"),
        "final_norm_b": get("transformer.ln_f.bias"),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = get("lm_head.weight")
    return out


def _phixtral_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Phixtral (legacy mixformer naming): one shared biased layernorm,
    fused mixer.Wqkv, and a router over phi-2 fc1/fc2 experts
    (moe.mlp.{e}.*; reference models/phixtral.py)."""
    p = f"transformer.h.{i}."
    H = config.hidden_size
    ln_w = get(p + "ln.weight")
    ln_b = get(p + "ln.bias")
    wqkv = get(p + "mixer.Wqkv.weight")  # [3H, H]
    bqkv = get(p + "mixer.Wqkv.bias")
    out = {
        "attn_norm": ln_w, "attn_norm_b": ln_b,
        "mlp_norm": ln_w, "mlp_norm_b": ln_b,
        "wq": wqkv[:H], "wk": wqkv[H:2 * H], "wv": wqkv[2 * H:],
        "bq": bqkv[:H], "bk": bqkv[H:2 * H], "bv": bqkv[2 * H:],
        "wo": get(p + "mixer.out_proj.weight"),
        "bo": get(p + "mixer.out_proj.bias"),
        "router": get(p + "moe.gate.weight"),
    }
    ups, bups, downs, bdowns = [], [], [], []
    for e in range(config.num_experts):
        ep = f"{p}moe.mlp.{e}."
        ups.append(get(ep + "fc1.weight"))
        bups.append(get(ep + "fc1.bias"))
        downs.append(get(ep + "fc2.weight"))
        bdowns.append(get(ep + "fc2.bias"))
    out["w_up_e"] = np.stack(ups)
    out["b_up_e"] = np.stack(bups)
    out["w_down_e"] = np.stack(downs)
    out["b_down_e"] = np.stack(bdowns)
    return out


def _phixtral_top(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    return {
        "embed": get("transformer.embd.wte.weight"),
        "final_norm": get("lm_head.ln.weight"),
        "final_norm_b": get("lm_head.ln.bias"),
        "lm_head": get("lm_head.linear.weight"),
        "lm_head_b": get("lm_head.linear.bias"),
    }


def _baichuan_m1_layer(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    """Baichuan-M1: fused W_pack qkv + per-kv-head kernel-2 conv taps
    (HF conv_k/conv_v [1, 1, Hkv, 1, 2] -> [Hkv, 2])."""
    p = f"model.layers.{i}."
    Hkv = config.num_key_value_heads
    return {
        "attn_norm": get(p + "input_layernorm.weight"),
        "mlp_norm": get(p + "post_attention_layernorm.weight"),
        "wqkv": get(p + "self_attn.W_pack.weight"),
        "wo": get(p + "self_attn.o_proj.weight"),
        "conv_k": get(p + "self_attn.conv_k").reshape(Hkv, 2).astype(np.float32),
        "conv_v": get(p + "self_attn.conv_v").reshape(Hkv, 2).astype(np.float32),
        "w_gate": get(p + "mlp.gate_proj.weight"),
        "w_up": get(p + "mlp.up_proj.weight"),
        "w_down": get(p + "mlp.down_proj.weight"),
    }


_FAMILY_LAYER = {
    "gemma2": _gemma2_layer,
    "gemma3": _gemma3_layer,
    "gemma3_text": _gemma3_layer,
    "phi3": _phi3_layer,
    "phi3_v": _phi3_layer,  # text half is phi3 (vision keys not loaded)
    "baichuan": _baichuan_layer,
    "internlm2": _internlm2_layer,
    # xcomposer2: internlm2 names; Plora_A/B image-path keys are ignored
    "internlmxcomposer2": _internlm2_layer,
    "starcoder2": _starcoder2_layer,
    "glm": _glm_layer,
    "chatglm": _chatglm_layer,
    "chatglm4v": _chatglm_layer,
    "qwen2_vl": _qwen2_vl_layer,
    "mpt": _mpt_layer,
    "gpt2": _gpt2_layer,
    "bloom": _bloom_layer,
    "gpt_neox": _gptneox_layer,
    "mixtral": _mixtral_layer,
    "qwen2_moe": _qwen2_moe_layer,
    "rwkv": _rwkv_layer,
    "rwkv5": _rwkv_layer,
    "falcon": _falcon_layer,
    "qwen3": _qwen3_layer,
    "qwen3_moe": _qwen3_moe_layer,
    "phi": _phi_layer,
    "cohere": _cohere_layer,
    "yuan": _yuan_layer,
    "minicpmv": _minicpmv_layer,
    "minicpmo": _minicpmv_layer,  # same llm. prefix, qwen2 layout
    "megrezo": _minicpmv_layer,  # Megrez-3B-Omni: llama llm under llm.
    "qwen2_audio": _qwen2_audio_layer,
    "internvl": _internvl_layer,
    "janus": _janus_layer,
    "qwen": _qwen_layer,
    "deci": _deci_layer,
    "gpt_bigcode": _gptbigcode_layer,
    "phixtral": _phixtral_layer,
    "baichuan_m1": _baichuan_m1_layer,
}

_FAMILY_TOP = {
    "baichuan": _baichuan_top,
    "internlm2": _internlm2_top,
    "internlmxcomposer2": _internlm2_top,
    "chatglm": _chatglm_top,
    "chatglm4v": _chatglm_top,
    "qwen2_vl": _qwen2_vl_top,
    "mpt": _mpt_top,
    "gpt2": _gpt2_top,
    "bloom": _bloom_top,
    "gpt_neox": _gptneox_top,
    "rwkv": _rwkv_top,
    "rwkv5": _rwkv_top,
    "falcon": _falcon_top,
    "phi": _phi_top,
    "gemma3": _gemma3_top,
    "gemma3_text": _gemma3_top,
    "minicpmv": _minicpmv_top,
    "minicpmo": _minicpmv_top,  # same llm. prefix
    "megrezo": _minicpmv_top,
    "qwen2_audio": _qwen2_audio_top,
    "internvl": _internvl_top,
    "janus": _janus_top,
    "qwen": _qwen_top,
    "gpt_bigcode": _gptbigcode_top,
    "phixtral": _phixtral_top,
}


def _mllama_tree(config: ModelConfig, get: Get, quant) -> tuple[list, list, dict]:
    """Mllama's decoder is heterogeneous: self-attn layers (llama names)
    interleaved with cross-attn layers at config.cross_attention_layers
    (HF modeling_mllama; reference models/mllama.py). Returns
    (self_layer_dicts, cross_layer_dicts, top_dict) with `quant` applied
    per layer as tensors stream in (peak host memory ~one fp32 layer) —
    the self stack keeps llama's leaf names so models/mllama.py scans it
    unchanged. Accepts both MllamaForCausalLM (`model.`) and
    MllamaForConditionalGeneration (`language_model.model.`) prefixes."""

    def g(name):
        try:
            return get(name)
        except KeyError:
            return get("language_model." + name)

    cross_set = set(config.cross_attention_layers or ())
    self_dicts, cross_dicts = [], []
    for i in range(config.num_hidden_layers):
        p = f"model.layers.{i}."
        if i in cross_set:
            cross_dicts.append({
                "attn_norm": g(p + "input_layernorm.weight"),
                "mlp_norm": g(p + "post_attention_layernorm.weight"),
                "wq": g(p + "cross_attn.q_proj.weight"),
                "wk": g(p + "cross_attn.k_proj.weight"),
                "wv": g(p + "cross_attn.v_proj.weight"),
                "wo": g(p + "cross_attn.o_proj.weight"),
                "q_norm": g(p + "cross_attn.q_norm.weight"),
                "k_norm": g(p + "cross_attn.k_norm.weight"),
                "attn_gate": np.asarray(g(p + "cross_attn_attn_gate")).reshape(()),
                "mlp_gate": np.asarray(g(p + "cross_attn_mlp_gate")).reshape(()),
                "w_gate": g(p + "mlp.gate_proj.weight"),
                "w_up": g(p + "mlp.up_proj.weight"),
                "w_down": g(p + "mlp.down_proj.weight"),
            })
            cross_dicts[-1] = {k: quant(k, v) for k, v in cross_dicts[-1].items()}
        else:
            self_dicts.append(
                {k: quant(k, v)
                 for k, v in _llama_layer(config, i, g).items()}
            )
    top = {
        "embed": g("model.embed_tokens.weight"),  # vocab_size + 8 rows
        "final_norm": g("model.norm.weight"),
        "lm_head": g("lm_head.weight"),
    }
    return self_dicts, cross_dicts, top


def _deepseek_tree(config: ModelConfig, get: Get, quant) -> tuple[list, list, dict]:
    """DeepSeek-V2/V3 / MiniCPM3 (HF modeling_deepseek_v2/v3; reference
    models/minicpm3.py): MLA projections per layer — kv_b_proj splits
    into the per-head W_uk/W_uv factors models/deepseek.py absorbs — and
    a heterogeneous stack: the first first_k_dense_replace layers carry
    a dense MLP, the rest DeepSeek-MoE. Returns (dense_dicts, moe_dicts,
    top) with `quant` applied per layer as tensors stream in."""
    from bigdl_tpu.models.deepseek import _dims, num_dense_layers

    H, dn, dr, dv, r = _dims(config)
    K = num_dense_layers(config)

    def attn(p):
        out = {
            "attn_norm": get(p + "input_layernorm.weight"),
            "mlp_norm": get(p + "post_attention_layernorm.weight"),
            "w_dkv": get(p + "self_attn.kv_a_proj_with_mqa.weight"),
            "kv_norm": get(p + "self_attn.kv_a_layernorm.weight"),
            "wo": get(p + "self_attn.o_proj.weight"),
        }
        kvb = np.asarray(get(p + "self_attn.kv_b_proj.weight"))
        kvb = kvb.reshape(H, dn + dv, r)
        out["w_uk"] = kvb[:, :dn]
        out["w_uv"] = kvb[:, dn:]
        if config.q_lora_rank:
            out["w_dq"] = get(p + "self_attn.q_a_proj.weight")
            out["q_norm"] = get(p + "self_attn.q_a_layernorm.weight")
            out["w_uq"] = get(p + "self_attn.q_b_proj.weight")
        else:
            out["wq"] = get(p + "self_attn.q_proj.weight")
        return out

    dense_dicts, moe_dicts = [], []
    for i in range(config.num_hidden_layers):
        p = f"model.layers.{i}."
        d = attn(p)
        if i < K:
            d["w_gate"] = get(p + "mlp.gate_proj.weight")
            d["w_up"] = get(p + "mlp.up_proj.weight")
            d["w_down"] = get(p + "mlp.down_proj.weight")
            dense_dicts.append({k: quant(k, v) for k, v in d.items()})
        else:
            E = config.num_experts
            d["router"] = get(p + "mlp.gate.weight")
            if (config.topk_method or "") == "noaux_tc":
                d["e_bias"] = get(p + "mlp.gate.e_score_correction_bias")
            d["w_gate_e"] = np.stack(
                [get(p + f"mlp.experts.{e}.gate_proj.weight") for e in range(E)]
            )
            d["w_up_e"] = np.stack(
                [get(p + f"mlp.experts.{e}.up_proj.weight") for e in range(E)]
            )
            d["w_down_e"] = np.stack(
                [get(p + f"mlp.experts.{e}.down_proj.weight") for e in range(E)]
            )
            if config.n_shared_experts:
                d["w_gate_s"] = get(p + "mlp.shared_experts.gate_proj.weight")
                d["w_up_s"] = get(p + "mlp.shared_experts.up_proj.weight")
                d["w_down_s"] = get(p + "mlp.shared_experts.down_proj.weight")
            moe_dicts.append({k: quant(k, v) for k, v in d.items()})
    top = {
        "embed": get("model.embed_tokens.weight"),
        "final_norm": get("model.norm.weight"),
    }
    if not config.tie_word_embeddings:
        top["lm_head"] = get("lm_head.weight")
    return dense_dicts, moe_dicts, top


def layer_tensors(config: ModelConfig, i: int, get: Get) -> dict[str, np.ndarray]:
    fn = _FAMILY_LAYER.get(config.model_type, _llama_layer)
    return fn(config, i, get)


def top_tensors(config: ModelConfig, get: Get) -> dict[str, np.ndarray]:
    fn = _FAMILY_TOP.get(config.model_type, _llama_top)
    return fn(config, get)


# ---------------------------------------------------------------------------
# tree assembly
# ---------------------------------------------------------------------------

def _stack_qtensors(qs: list[QTensor]) -> QTensor:
    from bigdl_tpu.quant.qtensor import map_arrays_multi

    return map_arrays_multi(qs, jnp.stack)


def params_from_state_dict(
    config: ModelConfig,
    get_tensor: Get,
    qtype: str = "sym_int4",
    dtype=jnp.bfloat16,
    lm_head_qtype: Optional[str] = None,
) -> dict:
    """Build the model param pytree from a tensor-name accessor.

    `get_tensor` returns a numpy array for an HF tensor name (backed by a
    dict for tests, or by lazy safetensors shards for real checkpoints).
    Weights are quantized layer by layer as they stream in, then stacked
    along the leading (scan) axis. lm_head_qtype overrides the head's
    format (mixed-precision head, reference IPEX_LLM_LAST_LM_HEAD /
    gguf_mixed_qtype behavior).
    """
    from bigdl_tpu.quant.qtypes import split_mixed_qtype

    qtype, head_default = split_mixed_qtype(qtype)
    lm_head_qtype = lm_head_qtype or head_default
    spec = resolve_qtype(qtype)
    head_spec = resolve_qtype(lm_head_qtype) if lm_head_qtype else spec

    def maybe_quant(name: str, arr):
        if isinstance(arr, QTensor):  # exact GPTQ/AWQ repack (autoq.py)
            return arr
        use_spec = head_spec if name == "lm_head" else spec
        if (not use_spec.is_dense) and (name in _QUANT_TARGETS or name == "lm_head"):
            from bigdl_tpu import native

            # native C++ packer (csrc/) for the ingest hot loop; bit-equal
            # jnp fallback otherwise
            qt = native.quantize_to_qtensor(
                np.asarray(arr, np.float32), use_spec.name
            )
            if qt is not None:
                return qt
            return quantize(jnp.asarray(arr, jnp.float32), use_spec.name)
        return jnp.asarray(arr).astype(dtype)

    def stack_dicts(dicts: list[dict]) -> dict:
        """Stack already-quantized per-layer dicts along a leading axis."""
        out = {}
        for k in dicts[0]:
            vals = [d[k] for d in dicts]
            if isinstance(vals[0], QTensor):
                out[k] = _stack_qtensors(vals)
            else:
                out[k] = jnp.stack(vals)
        return out

    if config.model_type in ("mllama", "mllama_text_model") \
            and config.cross_attention_layers:
        self_dicts, cross_dicts, top = _mllama_tree(
            config, get_tensor, maybe_quant
        )
        params = {"layers": stack_dicts(self_dicts),
                  "cross": stack_dicts(cross_dicts)}
        for k, v in top.items():
            params[k] = maybe_quant(k, v)
        return params

    if config.model_type in ("deepseek_v2", "deepseek_v3", "minicpm3"):
        dense_dicts, moe_dicts, top = _deepseek_tree(
            config, get_tensor, maybe_quant
        )
        params = {}
        if dense_dicts:
            params["layers"] = stack_dicts(dense_dicts)
        if moe_dicts:
            params["moe_layers"] = stack_dicts(moe_dicts)
        for k, v in top.items():
            params[k] = maybe_quant(k, v)
        return params

    # quantize layer by layer AS tensors stream in — peak host memory
    # stays ~one fp32 layer, not the whole checkpoint
    per_layer = [
        {k: maybe_quant(k, v)
         for k, v in layer_tensors(config, i, get_tensor).items()}
        for i in range(config.num_hidden_layers)
    ]
    params = {"layers": stack_dicts(per_layer)}
    for k, v in top_tensors(config, get_tensor).items():
        params[k] = maybe_quant(k, v)
    return params


def open_checkpoint(model_path: str):
    """Tensor getter over a local safetensors checkpoint dir (sharded or
    single-file): name -> np.ndarray. Floats arrive as fp32; integer
    tensors (GPTQ/AWQ packed words) keep their dtype — fp32 has 24
    mantissa bits and silently corrupts packed int32."""
    import torch  # lazy: only the ingest path touches torch
    from safetensors import safe_open  # lazy: heavy import

    index_path = os.path.join(model_path, "model.safetensors.index.json")
    if os.path.exists(index_path):
        with open(index_path) as f:
            weight_map = json.load(f)["weight_map"]
    else:
        single = os.path.join(model_path, "model.safetensors")
        with safe_open(single, framework="pt") as f:
            weight_map = {k: "model.safetensors" for k in f.keys()}

    handles: dict[str, object] = {}

    def get_tensor(name: str) -> np.ndarray:
        if name not in weight_map and name == "lm_head.weight":
            # some checkpoints tie without the flag; fall back to embeddings
            name = "model.embed_tokens.weight"
        if name not in weight_map:
            raise KeyError(
                f"checkpoint at {model_path} has no tensor {name!r} "
                f"({len(weight_map)} tensors present) — incomplete "
                "download, or a layout this translation doesn't cover?"
            )
        shard = weight_map[name]
        if shard not in handles:
            # torch framework: robust bf16/fp16 handling without ml_dtypes
            handles[shard] = safe_open(
                os.path.join(model_path, shard), framework="pt"
            )
        t = handles[shard].get_tensor(name)
        if t.is_floating_point():
            return t.to(dtype=torch.float32).numpy()
        return t.numpy()

    return get_tensor


def load_hf_checkpoint(
    model_path: str,
    qtype: str = "sym_int4",
    dtype=jnp.bfloat16,
    config: Optional[ModelConfig] = None,
) -> tuple[ModelConfig, dict, str]:
    """Load an HF-format local checkpoint directory (config.json +
    *.safetensors) into a quantized param tree.

    Returns (config, params, effective_qtype) — the effective qtype can
    differ from the request for GPTQ/AWQ checkpoints, whose packed codes
    live in asym_int4 (see _wrap_quantized)."""
    with open(os.path.join(model_path, "config.json")) as f:
        hf_config = json.load(f)
    if config is None:
        config = ModelConfig.from_hf_config(hf_config)

    get_tensor = open_checkpoint(model_path)
    quant_config = hf_config.get("quantization_config")
    if quant_config:
        get_tensor, qtype = _wrap_quantized(
            get_tensor, quant_config, config.model_type, qtype
        )
    params = params_from_state_dict(config, get_tensor, qtype, dtype)
    return config, params, qtype


# families whose layer builders slice/merge raw arrays (fused checkpoints) —
# they must receive fp32, never packed QTensors
_SPLIT_FAMILIES = {"phi3", "baichuan", "internlm2", "glm", "chatglm",
                   "chatglm4v", "falcon"}  # falcon ungroups fused query_key_value


def _wrap_quantized(get_tensor, quant_config: dict, model_type: str, qtype: str):
    """GPTQ/AWQ checkpoint: serve packed linears as exact asym_int4
    QTensors where possible (reference convert.py:379-455 requantizes; the
    exact mapping is lossless). Returns (getter, effective_qtype)."""
    from bigdl_tpu.convert.autoq import QuantCheckpointAdapter

    adapter = QuantCheckpointAdapter(get_tensor, quant_config)
    # the packed codes live in asym_int4; the default sym_int4 request is
    # upgraded to the exact container, any other explicit qtype requantizes
    if qtype == "sym_int4":
        qtype = "asym_int4"
    exact = qtype == "asym_int4" and model_type not in _SPLIT_FAMILIES

    def getter(name: str):
        if exact and name.endswith(".weight") and adapter.is_quantized(name):
            return adapter.get_weight(name)
        return adapter.get(name)

    return getter, qtype
