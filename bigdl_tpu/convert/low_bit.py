"""save_low_bit / load_low_bit — persist quantized models.

Equivalent of the reference's `save_low_bit`/`load_low_bit`
(transformers/model.py:58-104, optimize.py:40-57,137-196): quantize once,
reload in seconds without re-running conversion. Format: a directory with

    bigdl_tpu_config.json   {format_version, qtype, model_config, manifest}
    weights.npz             flat arrays; bf16/fp8 stored as integer views

The manifest records each pytree path, its dtype, and which paths fold
back into QTensor nodes, so loading needs no model code — it rebuilds the
exact param pytree.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant import QTensor

# v2: sym_int4/asym_int4/codebook4 nibble packing changed from
# interleaved (2i, 2i+1 per byte) to half-split (j, j+K/2 per byte) —
# see quant/numerics.pack_nibbles. v1 checkpoints would silently
# dequantize scrambled, so the version gate must reject them.
# v3: q4_k/q6_k storage moved from ggml super-block bytes to the planar
# layout (quant/kq_planar.py) with sub_scales/sub_mins fields.
# v4: the remaining low-bit formats moved to fused-GEMV layouts —
# q2_k/q3_k/q5_k from ggml super-block bytes to planar, and
# sym_int5/fp6/nf3 from int8 codes to packed bit planes
# (quant/numerics.pack_planes).
FORMAT_VERSION = 4

# qtypes whose storage layout changed at each version bump: older
# checkpoints load only if they contain none of the later-moved types
_MOVED_AT = {
    3: ("q4_k", "q6_k"),
    4: ("q2_k", "q3_k", "q5_k", "sym_int5", "fp6", "nf3"),
}

_VIEW_DTYPES = {
    "bfloat16": np.uint16,
    "float8_e4m3fn": np.uint8,
    "float8_e5m2": np.uint8,
}


def _encode(arr: jax.Array) -> tuple[np.ndarray, str]:
    a = np.asarray(arr)
    name = a.dtype.name
    if name in _VIEW_DTYPES:
        return a.view(_VIEW_DTYPES[name]), name
    return a, name


def _decode(a: np.ndarray, dtype_name: str) -> jnp.ndarray:
    if dtype_name in _VIEW_DTYPES:
        return jnp.asarray(a).view(jnp.dtype(dtype_name))
    return jnp.asarray(a)


def _flatten(tree: Any, prefix: str, arrays: dict, manifest: dict) -> None:
    if isinstance(tree, QTensor):
        from bigdl_tpu.quant.qtensor import ARRAY_FIELDS

        manifest[prefix] = {"kind": "qtensor", "qtype": tree.qtype}
        for field in ARRAY_FIELDS:
            val = getattr(tree, field)
            if val is not None:
                arr, dt = _encode(val)
                arrays[f"{prefix}@{field}"] = arr
                manifest[f"{prefix}@{field}"] = {"kind": "array", "dtype": dt}
        return
    if isinstance(tree, dict):
        for k in sorted(tree):
            _flatten(tree[k], f"{prefix}.{k}" if prefix else k, arrays, manifest)
        return
    arr, dt = _encode(tree)
    arrays[prefix] = arr
    manifest[prefix] = {"kind": "array", "dtype": dt}


def save_low_bit(path: str, config: ModelConfig, params: dict, qtype: str) -> None:
    os.makedirs(path, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    manifest: dict[str, dict] = {}
    _flatten(params, "", arrays, manifest)
    np.savez(os.path.join(path, "weights.npz"), **arrays)
    meta = {
        "format_version": FORMAT_VERSION,
        "qtype": qtype,
        "model_config": dataclasses.asdict(config),
        "manifest": manifest,
    }
    with open(os.path.join(path, "bigdl_tpu_config.json"), "w") as f:
        json.dump(meta, f, indent=1)


def load_low_bit(path: str) -> tuple[ModelConfig, dict, str]:
    """Returns (config, params, qtype)."""
    with open(os.path.join(path, "bigdl_tpu_config.json")) as f:
        meta = json.load(f)
    ver = meta["format_version"]
    if ver != FORMAT_VERSION:
        # older versions are still bit-compatible unless the checkpoint
        # contains a qtype whose storage moved at a later version
        moved = [q for v, qs in _MOVED_AT.items() if v > ver for q in qs]
        ok = ver in (2, 3) and not any(
            info.get("qtype") in moved
            for info in meta["manifest"].values()
        )
        if not ok:
            raise ValueError(f"unsupported format_version {ver}")
    config = ModelConfig(**meta["model_config"])
    manifest = meta["manifest"]
    npz = np.load(os.path.join(path, "weights.npz"))

    params: dict = {}

    def put(path_key: str, value) -> None:
        parts = path_key.split(".")
        node = params
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    from bigdl_tpu.quant.qtensor import ARRAY_FIELDS

    for key, info in manifest.items():
        if info["kind"] == "qtensor":
            fields = {}
            for field in ARRAY_FIELDS:
                fkey = f"{key}@{field}"
                if fkey in manifest:
                    fields[field] = _decode(npz[fkey], manifest[fkey]["dtype"])
                else:
                    fields[field] = None
            put(key, QTensor(qtype=info["qtype"], **fields))
        elif "@" not in key:
            put(key, _decode(npz[key], info["dtype"]))
    return config, params, meta["qtype"]
