"""save_low_bit / load_low_bit — persist quantized models.

Equivalent of the reference's `save_low_bit`/`load_low_bit`
(transformers/model.py:58-104, optimize.py:40-57,137-196): quantize once,
reload in seconds without re-running conversion. Format: a directory with

    bigdl_tpu_config.json   {format_version, qtype, model_config,
                             manifest, integrity}
    weights.npz             flat arrays; bf16/fp8 stored as integer views

The manifest records each pytree path, its dtype, and which paths fold
back into QTensor nodes, so loading needs no model code — it rebuilds the
exact param pytree.

Durability (utils/durability.py): both files are written through the
atomic tmp+fsync+rename protocol, so a kill mid-save leaves the previous
checkpoint bit-identical; the `integrity` section records per-tensor
crc32/sha256 digests that `load_low_bit(verify="fast"|"full")` checks,
raising a structured IntegrityError (never a bare KeyError) that names
every corrupted / missing / extra tensor. `salvage=True` loads the valid
subset instead and returns the quarantine report. Low-bit formats make
this non-optional: a flipped byte in packed codes or scales doesn't
crash, it silently dequantizes garbage.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import warnings
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.quant import QTensor
from bigdl_tpu.utils import durability
from bigdl_tpu.utils.durability import IntegrityError

# v2: sym_int4/asym_int4/codebook4 nibble packing changed from
# interleaved (2i, 2i+1 per byte) to half-split (j, j+K/2 per byte) —
# see quant/numerics.pack_nibbles. v1 checkpoints would silently
# dequantize scrambled, so the version gate must reject them.
# v3: q4_k/q6_k storage moved from ggml super-block bytes to the planar
# layout (quant/kq_planar.py) with sub_scales/sub_mins fields.
# v4: the remaining low-bit formats moved to fused-GEMV layouts —
# q2_k/q3_k/q5_k from ggml super-block bytes to planar, and
# sym_int5/fp6/nf3 from int8 codes to packed bit planes
# (quant/numerics.pack_planes).
FORMAT_VERSION = 4

# qtypes whose storage layout changed at each version bump: older
# checkpoints load only if they contain none of the later-moved types
_MOVED_AT = {
    3: ("q4_k", "q6_k"),
    4: ("q2_k", "q3_k", "q5_k", "sym_int5", "fp6", "nf3"),
}

_VIEW_DTYPES = {
    "bfloat16": np.uint16,
    "float8_e4m3fn": np.uint8,
    "float8_e5m2": np.uint8,
}


def _encode(arr: jax.Array) -> tuple[np.ndarray, str]:
    a = np.asarray(arr)
    name = a.dtype.name
    if name in _VIEW_DTYPES:
        return a.view(_VIEW_DTYPES[name]), name
    return a, name


def _decode(a: np.ndarray, dtype_name: str) -> jnp.ndarray:
    if dtype_name in _VIEW_DTYPES:
        return jnp.asarray(a).view(jnp.dtype(dtype_name))
    return jnp.asarray(a)


def _flatten(tree: Any, prefix: str, arrays: dict, manifest: dict) -> None:
    if isinstance(tree, QTensor):
        from bigdl_tpu.quant.qtensor import ARRAY_FIELDS

        manifest[prefix] = {"kind": "qtensor", "qtype": tree.qtype}
        for field in ARRAY_FIELDS:
            val = getattr(tree, field)
            if val is not None:
                arr, dt = _encode(val)
                arrays[f"{prefix}@{field}"] = arr
                manifest[f"{prefix}@{field}"] = {"kind": "array", "dtype": dt}
        return
    if isinstance(tree, dict):
        for k in sorted(tree):
            _flatten(tree[k], f"{prefix}.{k}" if prefix else k, arrays, manifest)
        return
    arr, dt = _encode(tree)
    arrays[prefix] = arr
    manifest[prefix] = {"kind": "array", "dtype": dt}


# matches current + superseded weights archives AND their stale tmps
# ("weights-<token>.npz.tmp-<pid>"), which the post-commit GC sweeps;
# anchored so unrelated operator files (weights.npz.bak) are never swept
_WEIGHTS_RE = re.compile(r"^weights(-[0-9a-f]{8})?\.npz(\.tmp-\d+)?$")


def save_low_bit(path: str, config: ModelConfig, params: dict, qtype: str,
                 *, faults=None) -> None:
    """Atomic, digest-manifested save with ONE commit point: the config
    rename. A fresh save writes the documented `weights.npz`; an
    overwrite writes a uniquely-named `weights-<token>.npz` sibling
    (never touching the file the live config references), then commits
    the config (whose `weights_file` points at the new archive and
    whose integrity section was computed from it, in the same
    serialization pass), then garbage-collects the superseded archive.
    A kill at ANY instant therefore leaves the referenced (config,
    weights) pair complete: before the commit it is the old pair,
    after it the new one. `faults` threads a
    utils/diskfaults.DiskFaultInjector through both atomic writes
    (tests only)."""
    os.makedirs(path, exist_ok=True)
    arrays: dict[str, np.ndarray] = {}
    manifest: dict[str, dict] = {}
    _flatten(params, "", arrays, manifest)
    overwrite = os.path.exists(os.path.join(path, "bigdl_tpu_config.json"))
    wname = (f"weights-{os.urandom(4).hex()}.npz" if overwrite
             else "weights.npz")
    tensors: dict[str, dict] = {}
    durability.atomic_write(
        os.path.join(path, wname),
        lambda f: tensors.update(durability.write_npz(f, arrays)),
        faults=faults,
    )
    meta = {
        "format_version": FORMAT_VERSION,
        "qtype": qtype,
        "model_config": dataclasses.asdict(config),
        "manifest": manifest,
        "weights_file": wname,
        "integrity": durability.integrity_section(tensors),
    }
    durability.atomic_write(
        os.path.join(path, "bigdl_tpu_config.json"),
        lambda f: f.write(json.dumps(meta, indent=1).encode()),
        faults=faults,
    )
    # sweep superseded weights archives (and their stale tmps) ONLY
    # after observing that the commit actually landed: the on-disk
    # config must reference the new archive and the archive must exist.
    # A lost write on either file (drop_file) then degrades to detection
    # at load, never to deleting the only copy the surviving config
    # references.
    try:
        with open(os.path.join(path, "bigdl_tpu_config.json")) as f:
            committed = json.load(f).get("weights_file") == wname
    except (OSError, ValueError):  # pragma: no cover - racing reader
        committed = False
    if committed and os.path.exists(os.path.join(path, wname)):
        for name in os.listdir(path):
            if name != wname and _WEIGHTS_RE.match(name):
                try:
                    os.unlink(os.path.join(path, name))
                except OSError:  # pragma: no cover - racing cleanup
                    pass


def _check_version(meta: dict) -> None:
    ver = meta["format_version"]
    if ver != FORMAT_VERSION:
        # older versions are still bit-compatible unless the checkpoint
        # contains a qtype whose storage moved at a later version
        moved = [q for v, qs in _MOVED_AT.items() if v > ver for q in qs]
        ok = ver in (2, 3) and not any(
            info.get("qtype") in moved
            for info in meta["manifest"].values()
        )
        if not ok:
            raise ValueError(f"unsupported format_version {ver}")


def _read_arrays(
    path: str, meta: dict, verify: str,
) -> tuple[dict, dict, list, list]:
    """Read + verify every stored array (durability.verify_npz_members).
    Returns (arrays, corrupted, missing, extra); raises IntegrityError
    only for artifact-level failures (the weights archive gone or
    unreadable as a zip). Structural problems (missing/extra members,
    unreadable members) are detected in EVERY verify mode — only the
    digest comparison is mode-gated."""
    manifest = meta["manifest"]
    integrity = (meta.get("integrity") or {}).get("tensors")
    wname = meta.get("weights_file", "weights.npz")
    wpath = os.path.join(path, wname)
    expected = {k for k, v in manifest.items() if v["kind"] == "array"}
    if not os.path.exists(wpath):
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, missing=expected, detail=f"{wname} does not exist",
        )
    if integrity is None and verify == "full":
        warnings.warn(
            f"{path}: no integrity manifest (pre-durability checkpoint); "
            "digest verification skipped — re-save to add digests"
        )
    return durability.verify_npz_members(wpath, integrity, verify, expected)


def load_low_bit(
    path: str, *, verify: str = "fast", salvage: bool = False,
):
    """Returns (config, params, qtype) — or, with salvage=True,
    (config, params, qtype, report) where report is the un-raised
    IntegrityError (None when the checkpoint is clean) and `params`
    holds only the tensors that verified.

    verify: "off" skips digest comparison (structural and zip-level
    checks still apply), "fast" checks sizes/shapes/crc32, "full" adds
    sha256 plus numerical validation (NaN/inf scan of float tensors and
    scales, per-qtype scale-range sanity)."""
    durability.check_verify_mode(verify)
    with open(os.path.join(path, "bigdl_tpu_config.json")) as f:
        meta = json.load(f)
    missing_keys = [k for k in ("format_version", "qtype", "model_config",
                                "manifest") if k not in meta]
    if missing_keys:
        # parseable JSON with rotted key names must not KeyError deep
        # in the loader — it is corruption like any other
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, detail="damaged config record (missing keys: "
                         f"{', '.join(missing_keys)})",
        )
    _check_version(meta)
    config = ModelConfig(**meta["model_config"])
    manifest = meta["manifest"]
    arrays, corrupted, missing, extra = _read_arrays(path, meta, verify)
    if verify == "full":
        for fnd in durability.validate_numerics(arrays, manifest):
            corrupted.setdefault(fnd.tensor, f"{fnd.issue}: {fnd.detail}")
            arrays.pop(fnd.tensor, None)

    report = None
    if corrupted or missing or extra:
        durability.VERIFY_FAILURES.inc()
        report = IntegrityError(
            path, corrupted=corrupted, missing=missing, extra=extra,
        )
        if not salvage:
            raise report
        warnings.warn(f"salvage load: {report}")

    params: dict = {}
    quarantined: list[str] = []

    def put(path_key: str, value) -> None:
        parts = path_key.split(".")
        node = params
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    from bigdl_tpu.quant.qtensor import ARRAY_FIELDS

    for key, info in manifest.items():
        if info["kind"] == "qtensor":
            fields = {}
            ok = True
            for field in ARRAY_FIELDS:
                fkey = f"{key}@{field}"
                if fkey not in manifest:
                    fields[field] = None
                elif fkey in arrays:
                    fields[field] = _decode(arrays[fkey],
                                            manifest[fkey]["dtype"])
                else:  # a field of this QTensor is corrupt/missing:
                    ok = False  # quarantine the whole logical tensor
            if ok:
                put(key, QTensor(qtype=info["qtype"], **fields))
            else:
                quarantined.append(key)
        elif "@" not in key:
            if key in arrays:
                put(key, _decode(arrays[key], info["dtype"]))
            else:
                quarantined.append(key)
    if report is not None:
        report.quarantined_params = sorted(quarantined)
    if salvage:
        return config, params, meta["qtype"], report
    return config, params, meta["qtype"]


def verify_low_bit(path: str) -> durability.VerifyReport:
    """Full-mode per-tensor verification WITHOUT building the param tree
    (the `bigdl-tpu verify` CLI). Always runs integrity `full` plus
    numerical validation; never raises for tensor findings — they land
    in the report rows."""
    try:
        with open(os.path.join(path, "bigdl_tpu_config.json")) as f:
            meta = json.load(f)
        _check_version(meta)
        # pull the structure INSIDE the guard: a parseable-but-damaged
        # config (rot inside a key name) must yield a report, not a
        # bare KeyError from the verify CLI
        manifest = meta["manifest"]
        if not isinstance(manifest, dict):
            raise KeyError("manifest")
    except (OSError, ValueError, KeyError, TypeError) as e:
        return durability.VerifyReport(
            path, "low_bit", rows=[],
            detail=f"unreadable config: {type(e).__name__}: {e}",
        )
    try:
        arrays, corrupted, missing, extra = _read_arrays(path, meta, "full")
    except IntegrityError as e:
        return durability.VerifyReport(
            path, "low_bit", rows=durability.rows_from_error(e),
            detail=e.detail,
        )
    rows = durability.rows_from_error(IntegrityError(
        path, corrupted=corrupted, missing=missing, extra=extra,
    ))
    flagged = set(corrupted) | set(missing) | set(extra)
    for fnd in durability.validate_numerics(arrays, manifest):
        rows.append(durability.TensorReport(
            fnd.tensor, "numerics", f"{fnd.issue}: {fnd.detail}",
        ))
        flagged.add(fnd.tensor)
    rows += [
        durability.TensorReport(k, "ok")
        for k in sorted(arrays) if k not in flagged
    ]
    return durability.VerifyReport(path, "low_bit", rows=rows)
