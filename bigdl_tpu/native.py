"""ctypes bindings for the native host quantization library (csrc/).

Role-equivalent of the reference's ctypes layer over its prebuilt C++
quant kernels (`ggml/model/llama/llama_cpp.py` bindings consumed by
`low_bit_linear.py:104-258` in /root/reference), except the library is
built from source on first use (g++ is part of the toolchain; there is
no prebuilt-wheel channel). Falls back to the pure-jnp numerics when the
toolchain is unavailable — behavior is bit-identical either way.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.dirname(__file__)), "csrc", "quant_kernels.cpp")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_dir() -> str:
    d = os.environ.get("BIGDL_TPU_NATIVE_CACHE") or os.path.join(
        os.path.expanduser("~"), ".cache", "bigdl_tpu"
    )
    os.makedirs(d, exist_ok=True)
    return d


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    with _LOCK:
        if _LIB is not None or _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("BIGDL_TPU_DISABLE_NATIVE"):
            return None
        if not os.path.exists(_SRC):
            return None
        try:
            with open(_SRC, "rb") as f:
                tag = hashlib.sha256(f.read()).hexdigest()[:16]
            so = os.path.join(_build_dir(), f"quant_kernels_{tag}.so")
            if not os.path.exists(so):
                tmp = so + ".tmp"
                subprocess.run(
                    [
                        "g++", "-O3", "-march=native", "-fopenmp", "-shared",
                        "-fPIC", "-o", tmp, _SRC,
                    ],
                    check=True, capture_output=True, timeout=120,
                )
                os.replace(tmp, so)
            lib = ctypes.CDLL(so)
            I64, F32P = ctypes.c_int64, np.ctypeslib.ndpointer(np.float32, flags="C")
            U8P = np.ctypeslib.ndpointer(np.uint8, flags="C")
            U16P = np.ctypeslib.ndpointer(np.uint16, flags="C")
            I8P = np.ctypeslib.ndpointer(np.int8, flags="C")
            I32P = np.ctypeslib.ndpointer(np.int32, flags="C")
            lib.quantize_sym_int4.argtypes = [F32P, I64, I64, U8P, U16P]
            lib.quantize_asym_int4.argtypes = [F32P, I64, I64, U8P, U16P, U16P]
            lib.quantize_sym_int8.argtypes = [F32P, I64, I64, I8P, U16P]
            lib.quantize_codebook4.argtypes = [
                F32P, I64, I64, I64, F32P, I32P, ctypes.c_float, U8P, U16P,
            ]
            lib.dequantize_sym_int4.argtypes = [U8P, U16P, I64, I64, F32P]
            _LIB = lib
        except Exception:
            _LIB = None
        return _LIB


def available() -> bool:
    return _load() is not None


_CODEBOOK4 = ("nf4", "fp4")
SUPPORTED = ("sym_int4", "asym_int4", "sym_int8") + _CODEBOOK4


def quantize_np(x: np.ndarray, qtype: str):
    """Quantize [.., rows, k] fp32 → (data, scales f16, mins|None) numpy,
    layouts identical to quant.numerics.quantize_blockwise. Returns None
    when the native library is unavailable or the qtype unsupported."""
    lib = _load()
    if lib is None or qtype not in SUPPORTED:
        return None
    from bigdl_tpu.quant.numerics import _codebook_tables
    from bigdl_tpu.quant.qtypes import resolve_qtype

    spec = resolve_qtype(qtype)
    x = np.ascontiguousarray(x, np.float32)
    k = x.shape[-1]
    if k % spec.block_size != 0:
        return None
    lead = x.shape[:-1]
    rows = int(np.prod(lead)) if lead else 1
    nb = k // spec.block_size
    scales = np.empty((rows, nb), np.uint16)
    x2 = x.reshape(rows, k)

    if qtype == "sym_int4":
        data = np.empty((rows, k // 2), np.uint8)
        lib.quantize_sym_int4(x2, rows, k, data, scales)
        mins = None
    elif qtype == "asym_int4":
        data = np.empty((rows, k // 2), np.uint8)
        mins = np.empty((rows, nb), np.uint16)
        lib.quantize_asym_int4(x2, rows, k, data, scales, mins)
    elif qtype == "sym_int8":
        data = np.empty((rows, k), np.int8)
        lib.quantize_sym_int8(x2, rows, k, data, scales)
        mins = None
    else:  # nf4 / fp4
        cb, order, boundaries = _codebook_tables(qtype)
        data = np.empty((rows, k // 2), np.uint8)
        lib.quantize_codebook4(
            x2, rows, k, spec.block_size,
            np.ascontiguousarray(boundaries, np.float32),
            np.ascontiguousarray(order, np.int32),
            float(np.max(np.abs(cb))), data, scales,
        )
        mins = None

    data = data.reshape(*lead, data.shape[-1])
    scales = scales.reshape(*lead, nb).view(np.float16)
    if mins is not None:
        mins = mins.reshape(*lead, nb).view(np.float16)
    return data, scales, mins


def quantize_to_qtensor(x: np.ndarray, qtype: str):
    """NumPy → QTensor via the native packer; None if unavailable."""
    out = quantize_np(x, qtype)
    if out is None:
        return None
    import jax.numpy as jnp

    from bigdl_tpu.quant import QTensor

    data, scales, mins = out
    return QTensor(
        data=jnp.asarray(data),
        scales=jnp.asarray(scales),
        mins=None if mins is None else jnp.asarray(mins),
        qtype=qtype,
    )
