"""StreamingLLM-style attention sinks: unbounded generation in a fixed
cache (reference: example/GPU/Applications/streaming-llm — a wrapper
over the external streaming_llm package with start_size/recent_size;
here it is a first-class cache policy).

The window keeps the first `sink` tokens (attention sinks — the
softmax's always-attended anchors) plus a rolling region of the most
recent tokens. When the cache fills, the oldest `chunk` non-sink slots
are evicted at once by shifting the recent region left.

TPU-native design: everything stays static-shaped and in-jit. Keys are
stored rotated (the hot path is untouched), so eviction re-bases the
shifted keys' rope positions by applying the exact `-chunk`-step inverse
rotation — rope is a per-lane complex rotation, so rotate(k, p-c) ==
rotate(rotate(k, p), -c), and the yarn/longrope attention scale factors
cancel (the shift tables use scale 1). Positions therefore never exceed
`window`, which is what keeps quality inside the trained context (the
point of the original StreamingLLM positional re-basing).

Chunked eviction is both the perf and the precision lever: a shift
rewrites the whole cache (L*B*W*Hkv*D * 2 dtypes of HBM traffic), so
evicting `chunk` slots at once amortizes that to 1/chunk per token; and
each shift rounds the re-rotated keys back to the cache dtype (bf16 on
the generate path), so a key surviving the recent region is re-rounded
ceil((W - sink)/chunk) times instead of once per token — with the
default chunk of (window - sink + 7) // 8 that is <= 8 rounding events,
a worst-case random-walk of a few bf16 ulps. The rotation itself is
exact; the only approximation on eviction is that rounding.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.ops import apply_rotary_emb
from bigdl_tpu.ops.rope import make_inv_freq_scaled, rope_cos_sin


def default_chunk(window: int, sink: int) -> int:
    return max(1, (window - sink + 7) // 8)


def validate_streaming(
    config: ModelConfig, window: int, sink: int, chunk: int = 1
) -> None:
    if not 0 < sink < window:
        raise ValueError(f"need 0 < sink ({sink}) < window ({window})")
    if not 0 < chunk <= window - sink:
        raise ValueError(
            f"need 0 < chunk ({chunk}) <= window - sink ({window - sink})"
        )
    if config.learned_positions:
        raise NotImplementedError(
            "streaming sinks need relative positions; learned absolute "
            "position embeddings (gpt2-style) cannot be re-based"
        )
    if config.sliding_window:
        raise NotImplementedError(
            "sliding-window attention already bounds the KV span; "
            "combining it with sink eviction is not supported"
        )
    if config.mrope_section or config.rope_local_theta is not None:
        raise NotImplementedError(
            "streaming sinks support standard 1-D rope only"
        )


def make_evict(config: ModelConfig, window: int, sink: int,
               chunk: int = 1):
    """Returns a jit-safe fn(cache) -> cache that UNCONDITIONALLY evicts
    the oldest `chunk` non-sink slots (shift + rope re-basing). Used by
    make_sink_shift (behind the pos >= window condition) and by
    ChatSession's make-room loop before a turn's prefill."""
    validate_streaming(config, window, sink, chunk)
    use_rope = not config.alibi  # alibi shifts without re-rotation
    if use_rope:
        inv_freq, _ = make_inv_freq_scaled(
            config.rotary_dim, config.rope_theta, config.rope_scaling_dict,
            seq_len=window,
        )
        # chunk-step INVERSE rotation; attention scale deliberately 1 —
        # the stored keys already carry it, and the re-basing must not
        cos_mc, sin_mc = rope_cos_sin(
            jnp.full((1,), -chunk, jnp.int32), inv_freq,
            interleaved=config.rope_interleaved,
        )
        cos_mc, sin_mc = cos_mc[0], sin_mc[0]  # [R]

    def check(cache):
        if cache.k_scale is not None:
            raise NotImplementedError(
                "streaming sinks over an fp8-quantized cache would need a "
                "dequant-rotate-requant pass; use quantize_kv=False"
            )
        if cache.rope_base is not None:
            raise NotImplementedError(
                "streaming sinks after SnapKV compression are unsupported"
            )
        if cache.pos.ndim != 0:
            raise NotImplementedError(
                "streaming sinks run on the aligned generate path "
                "(scalar cache.pos), not the serving engine's per-row pool"
            )

        moved_k = cache.k[:, :, sink + chunk:]
        if use_rope:
            _, moved_k = apply_rotary_emb(
                moved_k, moved_k, cos_mc, sin_mc, config.rope_interleaved
            )
        pad_k = jnp.zeros_like(cache.k[:, :, :chunk])
        new_k = jnp.concatenate(
            [cache.k[:, :, :sink], moved_k, pad_k], axis=2
        )
        new_v = jnp.concatenate(
            [cache.v[:, :, :sink], cache.v[:, :, sink + chunk:],
             jnp.zeros_like(cache.v[:, :, :chunk])], axis=2,
        )
        return dataclasses.replace(
            cache, k=new_k, v=new_v, pos=cache.pos - chunk
        )

    return check


def make_sink_shift(config: ModelConfig, window: int, sink: int,
                    chunk: int = 1):
    """Returns a jit-safe fn(cache) -> cache that evicts the oldest
    `chunk` non-sink slots when the cache is full (cache.pos >= window),
    else returns the cache unchanged. Scalar-pos (generate path) caches
    only."""
    evict = make_evict(config, window, sink, chunk)

    def shift(cache):
        return jax.lax.cond(cache.pos >= window, evict, lambda c: c, cache)

    return shift
