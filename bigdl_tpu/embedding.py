"""Embedding variants for memory-constrained serving.

TPU-native re-design of the reference's `transformers/embedding.py`:
- `LowBitEmbedding` (:179) — quantized table, per-row dequant at lookup
  (`xe_linear.dequantize_rows`): here the table is a QTensor and only the
  gathered rows are dequantized, in-graph.
- `CPUEmbedding` (:58) — table pinned in host RAM, device receives only
  the looked-up rows: `jax.pure_callback` performs the host gather, so
  HBM never holds the [V, H] matrix.
- `DiskEmbedding` (:96) — same, but the table is an np.memmap over a
  .npy file: rows stream from disk page cache per lookup.

`embed_lookup` dispatches on the leaf type; models/llama.embed_tokens
calls it, so any family supports all variants transparently.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.quant import QTensor
from bigdl_tpu.quant.numerics import dequantize_blockwise


class HostEmbedding:
    """Host-resident embedding table (CPU RAM or disk-backed memmap).

    Registered as a childless pytree node: it crosses jit boundaries as a
    static aux value (identity-hashed), and the lookup runs as a host
    callback — the device only ever sees [B, T, H] gathered rows.
    """

    def __init__(self, table: np.ndarray, dtype=jnp.bfloat16):
        self.table = table
        self.dtype = dtype
        self.vocab_size, self.hidden_size = table.shape

    @classmethod
    def from_file(cls, path: str, dtype=jnp.bfloat16) -> "HostEmbedding":
        """Disk-backed (reference DiskEmbedding): np.memmap keeps rows on
        disk until the page cache pulls them in."""
        return cls(np.load(path, mmap_mode="r"), dtype=dtype)

    def lookup(self, tokens: jax.Array) -> jax.Array:
        shape = jax.ShapeDtypeStruct(
            tokens.shape + (self.hidden_size,), np.float32
        )

        def host_gather(t):
            return np.asarray(self.table[np.asarray(t)], np.float32)

        out = jax.pure_callback(host_gather, shape, tokens, vmap_method="sequential")
        return out.astype(self.dtype)


jax.tree_util.register_pytree_node(
    HostEmbedding,
    lambda e: ((), e),
    lambda aux, _: aux,
)


def quantize_embedding(embed: jax.Array, qtype: str = "sym_int4") -> QTensor:
    """Reference LowBitEmbedding: quantize the table row-blockwise (each
    row's H dim carries the blocks, so a row dequantizes independently)."""
    from bigdl_tpu.quant import quantize

    return quantize(jnp.asarray(embed, jnp.float32), qtype)


def embed_lookup(embed: Any, tokens: jax.Array, compute_dtype=jnp.bfloat16) -> jax.Array:
    """Gather token embeddings from a dense array, QTensor (low-bit), or
    HostEmbedding (CPU/disk) table."""
    if isinstance(embed, HostEmbedding):
        return embed.lookup(tokens).astype(compute_dtype)
    if isinstance(embed, QTensor):
        # gather packed rows + their scales, then dequantize just those rows
        return embed.map_arrays(lambda a: a[tokens]).dequantize(compute_dtype)
    return embed.astype(compute_dtype)[tokens]
