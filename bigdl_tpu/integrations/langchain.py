"""LangChain adapter.

Equivalent of the reference's `langchain/llms/transformersllm.py`
(`TransformersLLM`, :61) and embeddings classes: wraps a TpuModel +
tokenizer behind LangChain's `LLM` interface. When langchain isn't
installed the same class still works as a plain callable text generator
(duck-typed `_call`/`invoke`), so the adapter is testable without the
framework.
"""

from __future__ import annotations

from typing import Any, List, Optional

try:  # langchain >= 0.1 layout
    from langchain_core.language_models.llms import LLM as _BaseLLM

    _HAVE_LANGCHAIN = True
except ImportError:  # standalone fallback with the same surface
    _HAVE_LANGCHAIN = False

    class _BaseLLM:  # type: ignore[no-redef]
        def invoke(self, prompt: str, **kw) -> str:
            return self._call(prompt, **kw)


class BigdlTpuLLM(_BaseLLM):
    """LangChain LLM over a bigdl_tpu model.

        llm = BigdlTpuLLM.from_model_id("/path/to/ckpt", load_in_low_bit="sym_int4")
        llm.invoke("Q: What is a TPU?\nA:")
    """

    model: Any = None
    tokenizer: Any = None
    max_new_tokens: int = 128
    temperature: float = 0.0

    def __init__(self, model=None, tokenizer=None, max_new_tokens: int = 128,
                 temperature: float = 0.0, **kw):
        if _HAVE_LANGCHAIN:
            super().__init__(
                model=model, tokenizer=tokenizer,
                max_new_tokens=max_new_tokens, temperature=temperature, **kw
            )
        else:
            self.model = model
            self.tokenizer = tokenizer
            self.max_new_tokens = max_new_tokens
            self.temperature = temperature

    class Config:
        arbitrary_types_allowed = True

    @classmethod
    def from_model_id(
        cls, model_id: str, load_in_low_bit: str = "sym_int4", **kw
    ) -> "BigdlTpuLLM":
        from bigdl_tpu.api import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            model_id, load_in_low_bit=load_in_low_bit
        )
        tokenizer = None
        try:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(model_id)
        except Exception:
            pass
        return cls(model=model, tokenizer=tokenizer, **kw)

    @property
    def _llm_type(self) -> str:
        return "bigdl-tpu"

    def _call(
        self,
        prompt: str,
        stop: Optional[List[str]] = None,
        run_manager: Any = None,
        **kwargs: Any,
    ) -> str:
        if self.tokenizer is None:
            raise ValueError("BigdlTpuLLM needs a tokenizer for text prompts")
        ids = list(self.tokenizer(prompt)["input_ids"])
        out = self.model.generate(
            [ids],
            max_new_tokens=kwargs.get("max_new_tokens", self.max_new_tokens),
            do_sample=self.temperature > 0,
            temperature=max(self.temperature, 1e-5),
            eos_token_id=self.tokenizer.eos_token_id,
        )
        text = self.tokenizer.decode(out[0].tolist(), skip_special_tokens=True)
        if stop:
            for s in stop:
                idx = text.find(s)
                if idx >= 0:
                    text = text[:idx]
        return text


class BigdlTpuEmbeddings:
    """LangChain-style embeddings over the BERT encoder family
    (reference langchain/embeddings/: TransformersEmbeddings). Duck-typed
    to the langchain Embeddings interface (embed_documents/embed_query),
    so it works with or without langchain installed."""

    def __init__(self, config, params, tokenizer, max_length: int = 256,
                 normalize: bool = True):
        self.config = config
        self.params = params
        self.tokenizer = tokenizer
        self.max_length = max_length
        self.normalize = normalize

    @classmethod
    def from_model_id(cls, model_id: str, qtype: str = "sym_int8", **kw):
        import json
        import os

        from transformers import AutoTokenizer

        from bigdl_tpu.convert.hf import open_checkpoint
        from bigdl_tpu.models import bert

        with open(os.path.join(model_id, "config.json")) as f:
            config = bert.BertConfig.from_hf_config(json.load(f))
        get = open_checkpoint(model_id)
        params = bert.params_from_hf(config, get, qtype=qtype)
        tok = AutoTokenizer.from_pretrained(model_id)
        return cls(config, params, tok.encode, **kw)

    def _embed(self, texts):
        from bigdl_tpu.models import bert

        tok = self.tokenizer
        # .encode first: HF tokenizers are ALSO callable, but __call__
        # returns a BatchEncoding dict, not ids
        enc = tok.encode if hasattr(tok, "encode") else tok

        class _T:
            encode = staticmethod(enc)

        return bert.embed_texts(
            self.config, self.params, _T(), list(texts),
            max_length=self.max_length, normalize=self.normalize,
        )

    def embed_documents(self, texts):
        return [list(map(float, row)) for row in self._embed(texts)]

    def embed_query(self, text: str):
        return self.embed_documents([text])[0]
