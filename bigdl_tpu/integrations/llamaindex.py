"""LlamaIndex adapter.

Equivalent of the reference's `llamaindex/llms/bigdlllm.py` (`IpexLLM`
class): exposes a TpuModel through LlamaIndex's CustomLLM interface when
llama_index is installed; otherwise a standalone class with `complete()`.
"""

from __future__ import annotations

from typing import Any, Optional

try:
    from llama_index.core.llms import (
        CompletionResponse,
        CustomLLM,
        LLMMetadata,
    )
    from llama_index.core.llms.callbacks import llm_completion_callback

    _HAVE_LLAMAINDEX = True
except ImportError:
    _HAVE_LLAMAINDEX = False

    class CustomLLM:  # type: ignore[no-redef]
        pass

    class CompletionResponse:  # type: ignore[no-redef]
        def __init__(self, text: str):
            self.text = text

    def llm_completion_callback():  # type: ignore[no-redef]
        def deco(fn):
            return fn

        return deco


class BigdlTpuLlamaIndexLLM(CustomLLM):
    model: Any = None
    tokenizer: Any = None
    max_new_tokens: int = 128
    context_window: int = 4096

    def __init__(self, model=None, tokenizer=None, max_new_tokens: int = 128,
                 context_window: int = 4096, **kw):
        if _HAVE_LLAMAINDEX:
            super().__init__(
                model=model, tokenizer=tokenizer,
                max_new_tokens=max_new_tokens,
                context_window=context_window, **kw
            )
        else:
            self.model = model
            self.tokenizer = tokenizer
            self.max_new_tokens = max_new_tokens
            self.context_window = context_window

    class Config:
        arbitrary_types_allowed = True

    @classmethod
    def from_model_id(cls, model_id: str, load_in_low_bit: str = "sym_int4", **kw):
        from bigdl_tpu.api import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            model_id, load_in_low_bit=load_in_low_bit
        )
        tokenizer = None
        try:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(model_id)
        except Exception:
            pass
        return cls(model=model, tokenizer=tokenizer, **kw)

    @property
    def metadata(self):
        if _HAVE_LLAMAINDEX:
            return LLMMetadata(
                context_window=self.context_window,
                num_output=self.max_new_tokens,
                model_name="bigdl-tpu",
            )
        return {"model_name": "bigdl-tpu"}

    @llm_completion_callback()
    def complete(self, prompt: str, **kwargs: Any) -> "CompletionResponse":
        ids = list(self.tokenizer(prompt)["input_ids"])
        out = self.model.generate(
            [ids],
            max_new_tokens=kwargs.get("max_new_tokens", self.max_new_tokens),
            eos_token_id=self.tokenizer.eos_token_id,
        )
        text = self.tokenizer.decode(out[0].tolist(), skip_special_tokens=True)
        return CompletionResponse(text=text)

    @llm_completion_callback()
    def stream_complete(self, prompt: str, **kwargs: Any):
        # single-shot fallback streaming (chunk = full completion)
        yield self.complete(prompt, **kwargs)
