"""Ecosystem integrations (reference: `langchain/` LLM+embeddings classes,
`llamaindex/` IpexLLM class — SURVEY.md §2.2). Imports are gated: each
adapter activates only when its framework is installed."""

__all__ = ["BigdlTpuLLM", "BigdlTpuLlamaIndexLLM"]


def __getattr__(name):
    if name == "BigdlTpuLLM":
        from bigdl_tpu.integrations.langchain import BigdlTpuLLM

        return BigdlTpuLLM
    if name == "BigdlTpuLlamaIndexLLM":
        from bigdl_tpu.integrations.llamaindex import BigdlTpuLlamaIndexLLM

        return BigdlTpuLlamaIndexLLM
    raise AttributeError(name)
