"""Stable Diffusion (diffusers) integration — gated.

Counterpart of the reference's sd support
(/root/reference/python/llm/src/ipex_llm/transformers/models/sd.py:
an `AttnProcessor2_0` subclass that routes diffusers UNet/transformer
attention through its fused SYCL sdp kernels, + `upcast_vae`). Here the
processor routes through `bigdl_tpu.ops.attention` (jnp; XLA fuses it),
so a diffusers pipeline whose tensors are torch-CPU round-trips through
the TPU for its attention — the same scope the reference covers (it
does not reimplement the UNet either; it accelerates attention inside
stock diffusers).

The `diffusers` package is NOT part of this environment's baked deps,
so everything here degrades with a clear ImportError at use time (the
module itself always imports). The processor is deliberately
torch<->jax boundary-explicit: inputs arrive as torch tensors from
diffusers' attention call protocol and return as torch tensors.
"""

from __future__ import annotations

from typing import Optional

HAVE_DIFFUSERS = True
try:  # pragma: no cover - environment without diffusers
    import diffusers  # noqa: F401
except Exception:
    HAVE_DIFFUSERS = False


class TpuAttnProcessor:
    """Drop-in diffusers attention processor (reference sd.py:45-143).

    Usage (requires `pip install diffusers`):

        pipe = StableDiffusionPipeline.from_pretrained(...)
        pipe.unet.set_attn_processor(TpuAttnProcessor())
    """

    def __init__(self):
        if not HAVE_DIFFUSERS:
            raise ImportError(
                "TpuAttnProcessor needs the `diffusers` package, which is "
                "not installed in this environment (pip install diffusers)"
            )

    def __call__(
        self,
        attn,
        hidden_states,
        encoder_hidden_states=None,
        attention_mask=None,
        temb=None,
        **kwargs,
    ):
        import jax.numpy as jnp
        import numpy as np
        import torch

        from bigdl_tpu.ops import attention as tpu_attention

        residual = hidden_states
        if attn.spatial_norm is not None:
            hidden_states = attn.spatial_norm(hidden_states, temb)

        input_ndim = hidden_states.ndim
        if input_ndim == 4:
            b, c, h, w = hidden_states.shape
            hidden_states = hidden_states.view(b, c, h * w).transpose(1, 2)

        if attn.group_norm is not None:
            hidden_states = attn.group_norm(
                hidden_states.transpose(1, 2)
            ).transpose(1, 2)

        query = attn.to_q(hidden_states)
        ctx = (hidden_states if encoder_hidden_states is None
               else encoder_hidden_states)
        if attn.norm_cross and encoder_hidden_states is not None:
            ctx = attn.norm_encoder_hidden_states(ctx)
        key = attn.to_k(ctx)
        value = attn.to_v(ctx)

        heads = attn.heads
        B, T, _ = query.shape
        S = key.shape[1]

        def to_jax(t, n):
            return jnp.asarray(
                t.detach().to(torch.float32).numpy()
            ).reshape(B, n, heads, -1)

        mask = None
        if attention_mask is not None:
            am = attn.prepare_attention_mask(attention_mask, S, B)
            mask = jnp.asarray(
                am.detach().to(torch.float32).numpy()
            ).reshape(B, heads, 1, -1, S)  # additive bias [B,Hkv,G,T,S]

        out = tpu_attention(
            to_jax(query, T), to_jax(key, S), to_jax(value, S), mask
        )
        out = torch.from_numpy(np.asarray(out).reshape(B, T, -1)).to(
            residual.dtype
        )

        out = attn.to_out[0](out)
        out = attn.to_out[1](out)  # dropout (identity at inference)

        if input_ndim == 4:
            out = out.transpose(-1, -2).reshape(b, c, h, w)
        if attn.residual_connection:
            out = out + residual
        return out / attn.rescale_output_factor


def upcast_vae(pipe) -> None:
    """Run the VAE in float32 (reference sd.py:145-152: SD upscaler VAEs
    overflow in fp16)."""
    if not HAVE_DIFFUSERS:
        raise ImportError("upcast_vae needs the `diffusers` package")
    import torch

    pipe.vae.to(dtype=torch.float32)
