"""Observability layer shared by serving and training (docs/observability.md).

- tracing.py  — bounded ring-buffer span recorder (Chrome trace-event /
  Perfetto export) + crc-suffixed per-request JSONL log + trace summary
- profiler.py — guarded on-demand ``jax.profiler`` windows

The serving engine (serving/engine.py) and training supervisor
(train/supervisor.py) both record into the same :class:`TraceRecorder`
format, so a serving run and a training run open in the same Perfetto
UI with the same span vocabulary.
"""

from bigdl_tpu.obs.tracing import (
    RequestLog,
    TraceRecorder,
    format_summary,
    summarize_trace,
)

__all__ = [
    "TraceRecorder",
    "RequestLog",
    "summarize_trace",
    "format_summary",
    "ProfilerWindow",
    "PROFILER",
]


def __getattr__(name):
    if name in ("ProfilerWindow", "PROFILER"):  # lazy: keeps the
        # recorder importable in processes that never touch jax.profiler
        from bigdl_tpu.obs import profiler as _p

        return getattr(_p, name)
    raise AttributeError(name)
