"""Request-lifecycle tracing: a lock-cheap bounded span recorder with
Chrome trace-event export, plus a structured per-request JSONL log.

The reference stack's only serving observability is its vLLM fork's
Prometheus endpoint (SURVEY §L7) — counters tell you *that* p99 moved,
never *where* the time went inside a request. This module records the
full lifecycle (submit → queued → prefill → decode windows → preempt/
resume → finish) as spans and exports them in the Chrome trace-event
JSON format, so a serving run (or a training run — the supervisor
records into the same format) loads directly in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``.

Design constraints (docs/observability.md):

- **Tracing off ⇒ near-zero overhead.** Every record method returns
  after a single attribute check when ``enabled`` is False; the engine
  additionally guards its instrumentation sites on the same flag, so a
  production engine with tracing disabled pays one pointer load per
  hook. No lock is taken on the hot path even when enabled: the ring is
  a ``deque(maxlen=...)`` whose ``append`` is atomic under the GIL
  (single engine-thread writer for spans; handler threads only add
  submit/finish instants, which are themselves single appends).
- **Bounded.** The ring holds the newest ``capacity`` events; older
  ones are evicted and counted in ``dropped`` (approximately — the
  check races the append by design, a miscount of a few events under
  concurrent writers is acceptable for a drop *indicator*).
- **Injectable clock.** All timestamps flow through ``clock`` (default
  ``time.time``); the simulated-clock serving benchmark (ROADMAP) will
  drive the engine and this recorder from the same fake clock, so the
  traces it exports are in simulated seconds, not wall time.

Track model: ``tid`` 0 is the engine/trainer track (``decode_step``,
``train.step`` spans, occupancy counters); each request gets its own
track at ``tid = rid`` with strictly sequential spans — ``queued`` →
``prefill`` → ``decode`` windows → ``preempted`` → more ``decode``
windows — so nesting is trivially monotonic per track (the golden test
asserts it).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Callable, Optional

# cap on distinct thread_name metadata entries: a long-lived server sees
# unboundedly many rids, and the *name* table (unlike the ring) is not
# otherwise bounded
_MAX_NAMED_TRACKS = 4096


class TraceRecorder:
    """Bounded ring buffer of Chrome trace events.

    All public record methods take timestamps in SECONDS (float, the
    recorder's clock domain) and convert to the trace format's
    microseconds at append time. Callers that already hold a timestamp
    (the engine stamps once per step and reuses it) pass it explicitly;
    callers without one use :meth:`now`.
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True,
                 clock: Callable[[], float] = time.time):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.enabled = enabled
        self._clock = clock
        self._buf: "collections.deque[dict]" = collections.deque(
            maxlen=capacity
        )
        self._pid = os.getpid()
        self.dropped = 0
        self._named: set = set()

    # -- recording ----------------------------------------------------------

    def now(self) -> float:
        return self._clock()

    def _append(self, evt: dict) -> None:
        if len(self._buf) == self.capacity:
            self.dropped += 1  # approximate under racing writers (doc'd)
        self._buf.append(evt)

    def _name_track(self, tid: int, ts: float) -> None:
        """Perfetto-visible track label for a request's tid (emitted on
        first sight; the name table is capped, the ring may still evict
        the metadata event — both are display niceties, not data)."""
        if tid == 0 or tid in self._named or len(self._named) >= \
                _MAX_NAMED_TRACKS:
            return
        self._named.add(tid)
        self._append({
            "name": "thread_name", "ph": "M", "pid": self._pid,
            "tid": int(tid), "args": {"name": f"req {tid}"},
        })

    def complete(self, name: str, ts: float, dur: float, tid: int = 0,
                 cat: str = "engine", **args: Any) -> None:
        """One finished span: ``[ts, ts + dur]`` seconds."""
        if not self.enabled:
            return
        self._name_track(tid, ts)
        self._append({
            "name": name, "ph": "X", "cat": cat, "pid": self._pid,
            "tid": int(tid), "ts": int(ts * 1e6),
            "dur": max(int(dur * 1e6), 0), "args": args,
        })

    def instant(self, name: str, ts: Optional[float] = None, tid: int = 0,
                cat: str = "engine", **args: Any) -> None:
        if not self.enabled:
            return
        if ts is None:
            ts = self._clock()
        self._name_track(tid, ts)
        self._append({
            "name": name, "ph": "i", "s": "t", "cat": cat,
            "pid": self._pid, "tid": int(tid), "ts": int(ts * 1e6),
            "args": args,
        })

    def counter(self, name: str, ts: Optional[float] = None,
                **values: float) -> None:
        """Perfetto counter track (batch occupancy, queue depth, ...)."""
        if not self.enabled:
            return
        if ts is None:
            ts = self._clock()
        self._append({
            "name": name, "ph": "C", "pid": self._pid, "tid": 0,
            "ts": int(ts * 1e6), "args": values,
        })

    # -- export -------------------------------------------------------------

    def events(self) -> list:
        """Snapshot of the ring (oldest first)."""
        return list(self._buf)

    def clear(self) -> None:
        self._buf.clear()
        self._named.clear()
        self.dropped = 0

    def status(self) -> dict:
        return {"enabled": self.enabled, "events": len(self._buf),
                "capacity": self.capacity, "dropped": self.dropped}

    @staticmethod
    def _sanitize_args(evt: dict) -> dict:
        """Replace non-finite arg values with None: a NaN loss — the
        exact anomaly tracing exists to capture — must not turn the
        whole export into non-RFC-8259 JSON (`NaN` tokens) that
        Perfetto and strict parsers reject."""
        import math

        def bad(v):
            return isinstance(v, float) and not math.isfinite(v)

        args = evt.get("args")
        if args and any(bad(v) for v in args.values()):
            evt = dict(evt)
            evt["args"] = {k: (None if bad(v) else v)
                           for k, v in args.items()}
        return evt

    def export(self, path: Optional[str] = None) -> dict:
        """The Chrome trace-event object (``{"traceEvents": [...]}``),
        optionally written to ``path`` — the file loads as-is in
        Perfetto / ``chrome://tracing``. Non-finite arg values (NaN
        losses, ...) are exported as null to keep the JSON standard.
        The file commits through the atomic tmp+fsync+rename protocol:
        a SIGTERM mid-dump must leave either the previous export or the
        complete new one, never a torn, Perfetto-unloadable JSON."""
        obj = {"traceEvents": [self._sanitize_args(e)
                               for e in self.events()],
               "displayTimeUnit": "ms",
               "otherData": {"dropped_events": self.dropped}}
        if path is not None:
            from bigdl_tpu.utils.durability import atomic_write

            data = json.dumps(obj, separators=(",", ":"),
                              allow_nan=False).encode("utf-8")
            atomic_write(path, lambda f: f.write(data))
        return obj


class RequestLog:
    """Structured per-request JSONL log of *derived* timings (queue
    wait, TTFT, time-per-output-token, preempted time) — one record per
    finished request, in the serving journal's tab+crc32 line discipline
    (`serving/journal.crc_line`), so interior rot in a long-lived log is
    detectable and the two on-disk line formats cannot drift.

    Thread-safe: shed records come from handler threads while the
    engine thread writes completions. Write failures degrade to no-ops
    (observability must never take the engine down)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = None
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            self._f = open(path, "a", encoding="utf-8")
        except OSError:  # pragma: no cover - read-only mount
            self._f = None

    def write(self, record: dict) -> None:
        if self._f is None:
            return
        from bigdl_tpu.serving.journal import crc_line

        line = crc_line(json.dumps(record, separators=(",", ":")))
        try:
            with self._lock:
                self._f.write(line + "\n")
                self._f.flush()
        except (OSError, ValueError):  # pragma: no cover - closed/full
            pass

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                finally:
                    self._f = None

    @staticmethod
    def read(path: str) -> list:
        """Decode a request log: crc-mismatched / torn lines skipped
        (same tolerance as the journal scan)."""
        from bigdl_tpu.serving.journal import split_crc_line

        if not os.path.exists(path):
            return []
        out = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                body, ok = split_crc_line(line)
                if ok is False:
                    continue
                try:
                    out.append(json.loads(body))
                except json.JSONDecodeError:
                    continue
        return out


# ---------------------------------------------------------------------------
# trace summarization (the CLI's `bigdl-tpu trace summarize`)
# ---------------------------------------------------------------------------

def _pct(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    i = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[i]


def summarize_trace(trace) -> dict:
    """Reduce a trace (the export dict, or a bare event list) to a
    latency table: per span name — count / total / mean / p50 / p99 /
    max milliseconds; plus request-level stats derived from ``finish``
    instants (ttft / queue_wait / preempted seconds, finish reasons)."""
    events = trace.get("traceEvents", []) if isinstance(trace, dict) \
        else list(trace)
    spans: dict = {}
    reqs: dict = {"ttft_s": [], "queue_wait_s": [], "preempted_s": [],
                  "finish_reasons": {}}
    for e in events:
        ph = e.get("ph")
        if ph == "X":
            spans.setdefault(e.get("name", "?"), []).append(
                e.get("dur", 0) / 1e3  # µs -> ms
            )
        elif ph == "i" and e.get("name") == "finish":
            args = e.get("args", {})
            reason = args.get("finish_reason", "?")
            reqs["finish_reasons"][reason] = \
                reqs["finish_reasons"].get(reason, 0) + 1
            for k in ("ttft_s", "queue_wait_s", "preempted_s"):
                v = args.get(k)
                if isinstance(v, (int, float)):
                    reqs[k].append(float(v))
    table = {}
    for name, durs in spans.items():
        durs.sort()
        table[name] = {
            "count": len(durs),
            "total_ms": round(sum(durs), 3),
            "mean_ms": round(sum(durs) / len(durs), 3),
            "p50_ms": round(_pct(durs, 0.50), 3),
            "p99_ms": round(_pct(durs, 0.99), 3),
            "max_ms": round(durs[-1], 3),
        }
    req_stats = {"finish_reasons": reqs["finish_reasons"]}
    for k in ("ttft_s", "queue_wait_s", "preempted_s"):
        vals = sorted(reqs[k])
        if vals:
            req_stats[k] = {
                "count": len(vals),
                "mean": round(sum(vals) / len(vals), 6),
                "p50": round(_pct(vals, 0.50), 6),
                "p99": round(_pct(vals, 0.99), 6),
            }
    return {"spans": table, "requests": req_stats}


def format_summary(summary: dict) -> str:
    """Human-readable latency table for the CLI."""
    lines = [f"{'span':<14}{'count':>8}{'mean ms':>10}{'p50 ms':>10}"
             f"{'p99 ms':>10}{'max ms':>10}{'total ms':>11}"]
    lines.append("-" * len(lines[0]))
    for name in sorted(summary.get("spans", {})):
        s = summary["spans"][name]
        lines.append(
            f"{name:<14}{s['count']:>8}{s['mean_ms']:>10.3f}"
            f"{s['p50_ms']:>10.3f}{s['p99_ms']:>10.3f}"
            f"{s['max_ms']:>10.3f}{s['total_ms']:>11.3f}"
        )
    req = summary.get("requests", {})
    if req.get("finish_reasons"):
        lines.append("")
        lines.append("requests by finish_reason: " + ", ".join(
            f"{k}={v}" for k, v in sorted(req["finish_reasons"].items())
        ))
    for k, label in (("ttft_s", "TTFT"), ("queue_wait_s", "queue wait"),
                     ("preempted_s", "preempted")):
        if k in req:
            s = req[k]
            lines.append(
                f"{label}: n={s['count']} mean={s['mean'] * 1e3:.1f}ms "
                f"p50={s['p50'] * 1e3:.1f}ms p99={s['p99'] * 1e3:.1f}ms"
            )
    return "\n".join(lines)


def validate_nesting(events: list) -> list:
    """Spans that partially overlap a predecessor on the same track —
    `[]` means every track is monotonically nested (each pair of spans
    on a tid is either disjoint or fully contained). Test + CLI helper,
    not a hot path."""
    by_tid: dict = {}
    for e in events:
        if e.get("ph") == "X":
            by_tid.setdefault((e.get("pid"), e.get("tid")), []).append(e)
    bad = []
    for track in by_tid.values():
        track.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack: list = []  # enclosing spans' end times
        for e in track:
            end = e["ts"] + e.get("dur", 0)
            while stack and e["ts"] >= stack[-1]:
                stack.pop()
            if stack and end > stack[-1]:
                bad.append(e)
                continue
            stack.append(end)
    return bad
