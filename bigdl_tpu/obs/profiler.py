"""Guarded on-demand ``jax.profiler`` windows.

The XLA profiler is the ground truth for *device* time (HLO timelines,
TPU step traces), but ``start_trace`` is process-global and stateful:
two overlapping windows corrupt each other, and a ``stop_trace``
without a live window raises from deep inside XLA. This wrapper makes
the window an explicit, guarded resource so the ApiServer debug
endpoint and the ``bigdl-tpu trace profile-*`` CLI can drive it safely
against a live server: start is rejected while a window is open
(:class:`ProfilerBusy`), stop without a window is a structured
:class:`ProfilerIdle`, and the window's logdir/age are inspectable.

The profiler output (a TensorBoard/XProf logdir) is complementary to
`obs/tracing.py`'s host-side request spans: spans say *which request*
waited, the XLA trace says *which op* the device ran meanwhile.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class ProfilerBusy(RuntimeError):
    """start() while a window is already open."""


class ProfilerIdle(RuntimeError):
    """stop() with no window open."""


class ProfilerWindow:
    """One process-wide profiling window. ``start_fn``/``stop_fn``
    default to ``jax.profiler.start_trace``/``stop_trace`` (resolved
    lazily so importing this module never drags the profiler plugin
    in); tests inject stubs."""

    def __init__(self, start_fn: Optional[Callable] = None,
                 stop_fn: Optional[Callable] = None,
                 clock: Callable[[], float] = time.time):
        self._lock = threading.Lock()
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._clock = clock  # window-age timestamps (WCT001: injectable)
        self.logdir: Optional[str] = None
        self.started_at: Optional[float] = None

    def _fns(self):
        if self._start_fn is not None:
            return self._start_fn, self._stop_fn
        import jax.profiler as jp

        return jp.start_trace, jp.stop_trace

    def start(self, logdir: str) -> dict:
        if not logdir:
            raise ValueError("profiler window needs a logdir")
        with self._lock:
            if self.logdir is not None:
                raise ProfilerBusy(
                    f"a profiler window is already open (logdir="
                    f"{self.logdir}); stop it first"
                )
            start, _ = self._fns()
            start(logdir)  # raises before any state flips on failure
            self.logdir = logdir
            self.started_at = self._clock()
            return self.status()

    def stop(self) -> dict:
        with self._lock:
            if self.logdir is None:
                raise ProfilerIdle("no profiler window is open")
            _, stop = self._fns()
            logdir, t0 = self.logdir, self.started_at
            try:
                stop()
            finally:
                # the window is spent either way: a failed stop must not
                # wedge every later start behind ProfilerBusy
                self.logdir = None
                self.started_at = None
            return {"active": False, "logdir": logdir,
                    "seconds": round(self._clock() - (t0 or 0.0), 3)}

    def status(self) -> dict:
        out = {"active": self.logdir is not None, "logdir": self.logdir}
        if self.started_at is not None:
            out["seconds"] = round(self._clock() - self.started_at, 3)
        return out


#: the process-wide window the ApiServer debug endpoint drives
PROFILER = ProfilerWindow()
