"""Benchmark CSV -> HTML report, with delta highlighting against a
previous run.

Counterpart of the reference's reporting pipeline
(test/benchmark/csv_to_html.py + check_results.py in /root/reference:
CSV results render to an HTML table, per-metric deltas beyond a
threshold are colored, and the perf-regression CI gates on them).
stdlib-only (the reference uses pandas Styler)."""

from __future__ import annotations

import csv
import html
from typing import Optional

def _try_float(s):
    try:
        return float(s)
    except (TypeError, ValueError):
        return None


def read_csv(path: str) -> list[dict]:
    with open(path, newline="") as f:
        return list(csv.DictReader(f))


def diff_rows(
    rows: list[dict], prev: list[dict], key_fields: tuple = ("name", "api"),
) -> list[dict]:
    """Attach `<col>_delta_pct` columns comparing numeric fields against
    the previous run's row with the same key."""
    def key(r):
        return tuple(r.get(k, "") for k in key_fields)

    prev_by_key = {key(r): r for r in prev}
    out = []
    for r in rows:
        r = dict(r)
        p = prev_by_key.get(key(r))
        if p:
            for col in list(r.keys()):
                a, b = _try_float(r.get(col)), _try_float(p.get(col))
                if a is not None and b not in (None, 0.0):
                    r[f"{col}_delta_pct"] = round((a - b) / b * 100, 2)
        out.append(r)
    return out


def to_html(
    rows: list[dict],
    title: str = "bigdl-tpu benchmark",
    highlight_threshold: float = 3.0,
) -> str:
    """Render rows as a standalone HTML table; *_delta_pct cells beyond
    the threshold are colored (regressions red, improvements green —
    latency-style metrics, where higher is worse)."""
    if not rows:
        return f"<html><body><h2>{html.escape(title)}</h2><p>no rows</p></body></html>"
    # union over ALL rows (first-seen order): a first row without a
    # previous-run match has no *_delta_pct keys, which must not drop the
    # delta columns for the rows that do
    cols = list(dict.fromkeys(k for r in rows for k in r.keys()))
    head = "".join(f"<th>{html.escape(c)}</th>" for c in cols)
    body = []
    for r in rows:
        cells = []
        for c in cols:
            v = r.get(c, "")
            style = ""
            if c.endswith("_delta_pct"):
                f = _try_float(v)
                if f is not None and abs(f) >= highlight_threshold:
                    color = "#fadbd8" if f > 0 else "#d5f5e3"
                    style = f' style="background-color:{color}"'
            cells.append(f"<td{style}>{html.escape(str(v))}</td>")
        body.append("<tr>" + "".join(cells) + "</tr>")
    return (
        "<html><head><meta charset='utf-8'>"
        "<style>table{border-collapse:collapse}td,th{border:1px solid #999;"
        "padding:4px 8px;font-family:monospace;font-size:13px}</style>"
        f"</head><body><h2>{html.escape(title)}</h2>"
        f"<table><tr>{head}</tr>{''.join(body)}</table></body></html>"
    )


def csv_to_html(
    csv_path: str,
    out_path: str,
    prev_csv: Optional[str] = None,
    highlight_threshold: float = 3.0,
) -> str:
    rows = read_csv(csv_path)
    if prev_csv:
        rows = diff_rows(rows, read_csv(prev_csv))
    doc = to_html(rows, title=csv_path, highlight_threshold=highlight_threshold)
    # atomic commit (utils/durability, graftlint ATW001): a kill
    # mid-render must leave the previous report intact, not a torn file
    from bigdl_tpu.utils.durability import atomic_write

    atomic_write(out_path, lambda f: f.write(doc.encode("utf-8")))
    return out_path


def check_regressions(
    csv_path: str,
    prev_csv: str,
    latency_cols: tuple = ("first_cost_ms", "rest_cost_mean_ms"),
    threshold_pct: float = 5.0,
) -> list[str]:
    """The reference's check_results.py gate: latency columns that
    regressed more than threshold_pct vs the previous run. Empty list =
    gate passes."""
    rows = diff_rows(read_csv(csv_path), read_csv(prev_csv))
    failures = []
    for r in rows:
        for col in latency_cols:
            d = _try_float(r.get(f"{col}_delta_pct"))
            if d is not None and d > threshold_pct:
                failures.append(
                    f"{'/'.join(str(r.get(k, '')) for k in ('name', 'api'))}: "
                    f"{col} +{d}%"
                )
    return failures
