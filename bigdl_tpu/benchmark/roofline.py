"""Analytic bytes-moved / FLOPs model for the fused dequant matmul
family — the first increment of the ROADMAP "hardware-independent perf
gate".

Evaluates, on any machine with no device attached, the HBM traffic and
FLOP count of

* the fused Pallas kernel at the REAL block shapes it would pick (the
  tile policy is imported from `ops/pallas/tiling.py`, the same module
  the kernels use — the model cannot drift from the implementation), and
* the XLA dequant fallback it replaces (materialize a bf16 copy of W,
  then matmul),

so every perf-flavored change lands with a number even when the TPU
tunnel is down, and the next live window validates the model against
measured GB/s (BENCH_NOTES r03 banked 2.7x end-to-end for the GEMV
class; the ratio here is the bandwidth-bound prediction).

This module's own code needs no jax (only `quant.qtypes` + the tile
policy); importing it still initializes the bigdl_tpu package, so
bench.py's jax-free parent evaluates it in a CPU-pinned child.
"""

from __future__ import annotations

from bigdl_tpu.ops.pallas.tiling import (
    finest_split, pick_block_m, pick_block_o, round_up,
)
from bigdl_tpu.quant.qtypes import resolve_qtype

_X_BPE = 2  # activations cross as bf16 (the kernels' compute dtype)
_OUT_BPE = 2


def weight_bytes_per_row(qtype: str, K: int) -> int:
    """Stored bytes per output row: packed codes + every scale field —
    exactly what the kernel's weight-side BlockSpecs fetch."""
    spec = resolve_qtype(qtype)
    if spec.storage == "packed_u8":
        data = K // 2
    elif spec.storage == "packed_planes":
        data = K * sum(spec.planes) // 8
    else:  # int8 / fp8: one code byte per element
        data = K
    if spec.superblock:
        nsuper = K // spec.superblock
        nsub = K // spec.block_size
        scales = nsuper * 2 + nsub  # f16 d + integer sc
        if spec.asymmetric:
            scales += nsuper * 2 + nsub  # f16 dmin + integer mn
    else:
        scales = (K // spec.block_size) * 2  # f16 d
        if spec.asymmetric:
            scales += (K // spec.block_size) * 2  # f16 m
    return data + scales


def qmatmul_cost(qtype: str, M: int, K: int, O: int) -> dict:
    """Analytic cost of the fused dequant matmul y[M,O] = x[M,K] @ W^T.

    HBM traffic follows the kernel's actual fetch pattern (qmatmul._qmm):
    grid (m, o) with o innermost — the x row tile stays resident across a
    full sweep of weight tiles (fetched once per M tile == once total),
    packed weights are re-fetched once per M tile, the output is written
    once."""
    spec = resolve_qtype(qtype)
    row_bytes = weight_bytes_per_row(qtype, K)
    w_total = O * row_bytes

    block_m = pick_block_m(M, K)
    mp = round_up(max(M, 1), block_m)
    block_o = pick_block_o(O, row_bytes, cap=256)
    grid_m = mp // block_m

    fused_bytes = w_total * grid_m + mp * K * _X_BPE + mp * O * _OUT_BPE
    # XLA fallback: read packed W + scales, write the dequantized bf16
    # copy, read it back into the matmul, plus the same x/out traffic
    xla_bytes = (w_total + 2 * K * O * 2 + M * K * _X_BPE
                 + M * O * _OUT_BPE)
    flops = 2 * M * K * O
    return {
        "qtype": qtype,
        "shape": f"m{M}xk{K}xo{O}",
        "block_m": block_m,
        "block_o": block_o,
        "grid_m": grid_m,
        "weight_bits_per_el": round(row_bytes * 8 / K, 3),
        "fused_bytes": fused_bytes,
        "xla_dequant_bytes": xla_bytes,
        "flops": flops,
        "fused_intensity": round(flops / fused_bytes, 2),
        # bandwidth-bound speedup prediction for the fused path; > 1
        # means the fused kernel moves fewer HBM bytes for the same math
        "bytes_ratio_vs_xla": round(xla_bytes / fused_bytes, 2),
    }


def gemm_matrix(qtypes, Ms=(1, 128, 512, 2048), K: int = 4096,
                O: int = 4096) -> dict:
    """The bench.py analytic sweep: every fused format at decode and
    prefill shapes. Pure host math — lands a number with the tunnel
    down."""
    out = {}
    for qt in qtypes:
        spec = resolve_qtype(qt)
        if K % (spec.superblock or spec.block_size):
            continue
        for m in Ms:
            c = qmatmul_cost(qt, m, K, O)
            out[f"{qt}_m{m}"] = c
    return out
