"""Analytic bytes-moved / FLOPs model for the fused dequant matmul
family — the first increment of the ROADMAP "hardware-independent perf
gate".

Evaluates, on any machine with no device attached, the HBM traffic and
FLOP count of

* the fused Pallas kernel at the REAL block shapes it would pick (the
  tile policy is imported from `ops/pallas/tiling.py`, the same module
  the kernels use — the model cannot drift from the implementation), and
* the XLA dequant fallback it replaces (materialize a bf16 copy of W,
  then matmul),

so every perf-flavored change lands with a number even when the TPU
tunnel is down, and the next live window validates the model against
measured GB/s (BENCH_NOTES r03 banked 2.7x end-to-end for the GEMV
class; the ratio here is the bandwidth-bound prediction).

This module's own code needs no jax (only `quant.qtypes` + the tile
policy); importing it still initializes the bigdl_tpu package, so
bench.py's jax-free parent evaluates it in a CPU-pinned child.
"""

from __future__ import annotations

from bigdl_tpu.ops.pallas.tiling import (
    DX_ACC_BPE, chunk_target_dx, finest_split, flash_blocks,
    flash_live_blocks, pick_block_m, pick_block_m_dx, pick_block_o,
    pick_block_o_dw, round_up,
)
from bigdl_tpu.quant.qtypes import resolve_qtype

_X_BPE = 2  # activations cross as bf16 (the kernels' compute dtype)
_OUT_BPE = 2


def weight_bytes_per_row(qtype: str, K: int) -> int:
    """Stored bytes per output row: packed codes + every scale field —
    exactly what the kernel's weight-side BlockSpecs fetch."""
    spec = resolve_qtype(qtype)
    if spec.storage == "packed_u8":
        data = K // 2
    elif spec.storage == "packed_planes":
        data = K * sum(spec.planes) // 8
    else:  # int8 / fp8: one code byte per element
        data = K
    if spec.superblock:
        nsuper = K // spec.superblock
        nsub = K // spec.block_size
        scales = nsuper * 2 + nsub  # f16 d + integer sc
        if spec.asymmetric:
            scales += nsuper * 2 + nsub  # f16 dmin + integer mn
    else:
        scales = (K // spec.block_size) * 2  # f16 d
        if spec.asymmetric:
            scales += (K // spec.block_size) * 2  # f16 m
    return data + scales


def qmatmul_cost(qtype: str, M: int, K: int, O: int) -> dict:
    """Analytic cost of the fused dequant matmul y[M,O] = x[M,K] @ W^T.

    HBM traffic follows the kernel's actual fetch pattern (qmatmul._qmm):
    grid (m, o) with o innermost — the x row tile stays resident across a
    full sweep of weight tiles (fetched once per M tile == once total),
    packed weights are re-fetched once per M tile, the output is written
    once."""
    spec = resolve_qtype(qtype)
    row_bytes = weight_bytes_per_row(qtype, K)
    w_total = O * row_bytes

    block_m = pick_block_m(M, K)
    mp = round_up(max(M, 1), block_m)
    block_o = pick_block_o(O, row_bytes, cap=256)
    grid_m = mp // block_m

    fused_bytes = w_total * grid_m + mp * K * _X_BPE + mp * O * _OUT_BPE
    # XLA fallback: read packed W + scales, write the dequantized bf16
    # copy, read it back into the matmul, plus the same x/out traffic
    xla_bytes = (w_total + 2 * K * O * 2 + M * K * _X_BPE
                 + M * O * _OUT_BPE)
    flops = 2 * M * K * O
    return {
        "qtype": qtype,
        "shape": f"m{M}xk{K}xo{O}",
        "block_m": block_m,
        "block_o": block_o,
        "grid_m": grid_m,
        "weight_bits_per_el": round(row_bytes * 8 / K, 3),
        "fused_bytes": fused_bytes,
        "xla_dequant_bytes": xla_bytes,
        "flops": flops,
        "fused_intensity": round(flops / fused_bytes, 2),
        # bandwidth-bound speedup prediction for the fused path; > 1
        # means the fused kernel moves fewer HBM bytes for the same math
        "bytes_ratio_vs_xla": round(xla_bytes / fused_bytes, 2),
    }


def _storage_planes(spec) -> tuple:
    """The packed-plane tuple of a qtype's storage — the jax-free twin
    of ops/pallas/qdecode.spec_for's planes field (this module must not
    import jax; the mapping is 3 lines and covered by the DSP003
    storage-coverage check on the real spec_for)."""
    if spec.storage == "packed_u8":
        return (4,)
    if spec.storage == "packed_planes":
        return tuple(spec.planes)
    return ()


def bwd_dx_cost(qtype: str, M: int, K: int, O: int) -> dict:
    """Analytic cost of the fused backward dx[M,K] = g[M,O] @ dq(W) at
    qbackward's REAL tiles (tiling.pick_block_m_dx / chunk_target_dx —
    the same policy the kernel resolves, so model and implementation
    cannot drift).

    Fetch pattern (qbackward._dxmm): grid (m, o) with o innermost as the
    reduction sweep — the [block_m, K] f32 accumulator stays in VMEM
    scratch across a full weight sweep, so packed weights cross HBM once
    per M tile, g and dx exactly once, and the dequantized bf16 copy of
    W never exists in HBM. The XLA remat path it replaces writes that
    copy and reads it back (2*K*O*2) every train step."""
    spec = resolve_qtype(qtype)
    row_bytes = weight_bytes_per_row(qtype, K)
    w_total = O * row_bytes

    block_m = pick_block_m_dx(M, K)
    mp = round_up(max(M, 1), block_m)
    block_o = pick_block_o(O, row_bytes, cap=256)
    grid_m = mp // block_m
    persist = (block_m * K * DX_ACC_BPE + block_o * row_bytes
               + block_m * block_o * _X_BPE)
    ck = chunk_target_dx(block_o, block_m, persist,
                         finest_split(K, _storage_planes(spec)),
                         temp_bpe=20 if spec.asymmetric else 14)

    fused_bytes = w_total * grid_m + mp * O * _X_BPE + mp * K * _OUT_BPE
    xla_bytes = (w_total + 2 * K * O * 2 + M * O * _X_BPE
                 + M * K * _OUT_BPE)
    flops = 2 * M * K * O
    return {
        "kernel": "bwd_dx", "qtype": qtype,
        "shape": f"m{M}xk{K}xo{O}",
        "block_m": block_m, "block_o": block_o,
        "chunk": ck, "grid_m": grid_m,
        "fused_bytes": fused_bytes,
        "xla_remat_bytes": xla_bytes,
        "flops": flops,
        "fused_intensity": round(flops / fused_bytes, 2),
        "bytes_ratio_vs_xla": round(xla_bytes / fused_bytes, 2),
    }


def bwd_dw_cost(M: int, K: int, O: int) -> dict:
    """Analytic cost of the fused dW[O,K] = g^T @ x tiled accumulation
    (qbackward._dwmm) at its real tiles: grid (o, m) with m innermost,
    a [block_o, K] f32 accumulator per O tile. No dequant is involved —
    x is re-fetched once per O tile (the reduction-bound shape of any
    real tiled g^T @ x), so the honest ratio vs an ideal single-pass
    einsum sits near or below 1. The row exists for train-step pricing
    (sim/cost.train_step_s) and the unfrozen/bf16-shadow hook, not as a
    bytes win."""
    block_m = pick_block_m(M, max(K, O))
    mp = round_up(max(M, 1), block_m)
    block_o = pick_block_o_dw(O, K)
    op = round_up(O, block_o)
    grid_o = op // block_o
    fused_bytes = mp * op * _X_BPE + grid_o * mp * K * _X_BPE + op * K * _OUT_BPE
    xla_bytes = M * O * _X_BPE + M * K * _X_BPE + O * K * _OUT_BPE
    flops = 2 * M * K * O
    return {
        "kernel": "bwd_dw", "shape": f"m{M}xk{K}xo{O}",
        "block_m": block_m, "block_o": block_o, "grid_o": grid_o,
        "fused_bytes": fused_bytes,
        "xla_bytes": xla_bytes,
        "flops": flops,
        "fused_intensity": round(flops / fused_bytes, 2),
        "bytes_ratio_vs_xla": round(xla_bytes / fused_bytes, 2),
    }


def backward_matrix(qtypes, Ms=(1, 32, 512, 2048), K: int = 4096,
                    O: int = 4096) -> dict:
    """bench.py's analytic backward sweep: the fused dx kernel for every
    fused format at train-step row counts, plus the qtype-independent
    dW accumulation rows. Pure host math — the headline acceptance
    number (dx bytes ratio at M=512, sym_int4) lands with the tunnel
    down."""
    out = {}
    for qt in qtypes:
        spec = resolve_qtype(qt)
        if K % (spec.superblock or spec.block_size):
            continue
        for m in Ms:
            out[f"dx_{qt}_m{m}"] = bwd_dx_cost(qt, m, K, O)
    for m in Ms:
        out[f"dw_m{m}"] = bwd_dw_cost(m, K, O)
    return out


def lora_epilogue_cost(M: int, K: int, O: int, R: int,
                       fused: bool = True) -> dict:
    """Analytic cost of the multi-tenant LoRA epilogue
    ``((x @ A_cat^T) * gate) @ B_cat^T`` added to a y[M,O] = x[M,K]
    matmul, at the dequant-GEMM's real M tiles (the epilogue rides
    inside qmatmul's grid — ops/pallas/tiling.py is the shared policy).
    ``R`` is the total adapter width: the rank bucket for one shared
    adapter, or batch * rank-bucket for the serving engine's
    concatenated per-row form.

    Fused (qmatmul_lora): the x tile is already in VMEM, so the only
    NEW traffic is the adapter operands — A_cat once per M tile, B_cat
    tiles once per M-tile sweep, the gate once. Activation HBM round
    trips: **0**.

    XLA fallback (ops/linear.lora_epilogue): two extra activation round
    trips on top of the adapter stream — x is re-read by the first
    einsum, and the [M, O] delta is written then read back by the add
    (the [M, R] xa intermediate round-trips too, a third, rank-thin
    trip the summary number ignores)."""
    block_m = pick_block_m(M, K)
    mp = round_up(max(M, 1), block_m)
    grid_m = mp // block_m
    adapter_bytes = (R * K + O * R) * _X_BPE
    gate_bytes = mp * R * _X_BPE
    flops = 2 * M * R * (K + O)
    if fused:
        bytes_ = adapter_bytes * grid_m + gate_bytes
        round_trips = 0
    else:
        bytes_ = (adapter_bytes + M * K * _X_BPE
                  + 2 * M * R * _X_BPE + 2 * M * O * _OUT_BPE)
        round_trips = 2
    return {
        "kernel": "lora_epilogue",
        "shape": f"m{M}xk{K}xo{O}xr{R}",
        "fused": fused,
        "block_m": block_m,
        "grid_m": grid_m,
        "activation_round_trips": round_trips,
        "adapter_bytes": adapter_bytes,
        "bytes": bytes_,
        "flops": flops,
    }


# ---------------------------------------------------------------------------
# attention kernels (ISSUE 13 satellite): flash prefill +
# paged/dense decode attention, fp8-KV variants. Block/tile policy is
# imported from ops/pallas/tiling.py — the same module the kernels
# resolve their shapes from — so the sim's cost model (sim/cost.py) and
# the implementation cannot drift.
# ---------------------------------------------------------------------------


def flash_prefill_cost(T: int, S: int, Hq: int, Hkv: int, D: int,
                       B: int = 1, layers: int = 1,
                       quantize_kv: bool = False,
                       q_offset: int = 0, window=None) -> dict:
    """Analytic cost of the flash prefill kernel for a [T]-token chunk
    attending an [S]-slot cache, at the REAL (block_q, block_k) the
    kernel picks (tiling.flash_blocks) and with the kernel's own causal
    block-skip predicate (tiling.flash_live_blocks).

    Fetch pattern (flash_attention._flash BlockSpecs): the q block index
    map ignores j, so a q tile is fetched once per (b, h, i); k/v tiles
    are re-fetched per live (i, j) pair for every QUERY head (GQA
    grouping shares the HBM page only within one h's sweep). fp8 KV
    halves the k/v code bytes and adds f32 per-(slot, head) scales."""
    block_q, block_k = flash_blocks(T, S)
    live = flash_live_blocks(T, S, block_q, block_k,
                             q_offset=q_offset, window=window)
    Tp = round_up(T, block_q)
    kv_bpe = 1 if quantize_kv else 2
    q_bytes = B * Hq * Tp * D * _X_BPE
    kv_tile = block_k * D * kv_bpe + (block_k * 4 if quantize_kv else 0)
    kv_bytes = B * Hq * live * 2 * kv_tile  # k AND v
    o_bytes = B * Hq * Tp * D * _OUT_BPE
    # qk^T + av over the live blocks (the skipped blocks cost nothing —
    # the kernel's pl.when elides the whole compute body)
    flops = 4 * B * Hq * live * block_q * block_k * D
    total = layers * (q_bytes + kv_bytes + o_bytes)
    return {
        "kernel": "flash_prefill", "shape": f"t{T}xs{S}",
        "block_q": block_q, "block_k": block_k,
        "live_blocks": live, "quantize_kv": quantize_kv,
        "bytes": total, "flops": layers * flops,
        "intensity": round(layers * flops / max(total, 1), 2),
    }


def decode_attention_cost(pos, page: int, Hq: int, Hkv: int, D: int,
                          layers: int = 1, paged: bool = True,
                          quantize_kv: bool = False,
                          max_len: int = 0) -> dict:
    """Analytic cost of one batched decode-attention step over the rows'
    live KV. `pos` is the per-row written position (int or list of
    ints — the engine's cache.pos for the active slots).

    Paged (ops/pallas/paged_attention): grid (B, max_pages), one
    (page, Hkv, D) k and v tile per live page — pages past
    ceil(pos/page) all map to the scratch sink page 0, whose single tile
    stays HBM-resident, so the traffic model counts live pages only.
    Dense: each row streams its [max_len] cache rows (the dense decode
    path has no page table to skip dead slots by block). fp8 KV halves
    code bytes and adds the f32 per-(slot, head) scale planes."""
    rows = [pos] if isinstance(pos, int) else list(pos)
    kv_bpe = 1 if quantize_kv else 2
    if paged:
        pages = sum(-(-max(p, 1) // page) for p in rows)
        slots = pages * page
    else:
        if not max_len:
            raise ValueError("dense decode attention needs max_len")
        slots = len(rows) * max_len
        pages = 0
    slot_bytes = Hkv * D * kv_bpe + (Hkv * 4 if quantize_kv else 0)
    kv_bytes = 2 * slots * slot_bytes  # k AND v
    q_bytes = len(rows) * Hq * D * 4  # the kernel lifts q to f32
    o_bytes = len(rows) * Hq * D * _OUT_BPE
    flops = 4 * sum(max(p, 1) for p in rows) * Hq * D
    total = layers * (kv_bytes + q_bytes + o_bytes)
    return {
        "kernel": "paged_decode" if paged else "dense_decode",
        "batch": len(rows), "page": page if paged else None,
        "live_pages": pages, "kv_slots_touched": slots,
        "quantize_kv": quantize_kv,
        "bytes": total, "flops": layers * flops,
        "intensity": round(layers * flops / max(total, 1), 4),
    }


def attention_matrix(Ts=(128, 512, 2048), S_extra: int = 0,
                     Hq: int = 32, Hkv: int = 8, D: int = 128,
                     page: int = 64) -> dict:
    """bench.py's analytic attention sweep (child_analytic): flash
    prefill chunks and batched paged decode at llama3-class GQA shapes,
    bf16 and fp8 KV — pure host math, lands with the tunnel down."""
    out = {}
    for T in Ts:
        for qkv in (False, True):
            c = flash_prefill_cost(T, T + S_extra, Hq, Hkv, D,
                                   quantize_kv=qkv)
            out[f"flash_t{T}{'_fp8' if qkv else ''}"] = c
    for B in (1, 8, 32):
        for qkv in (False, True):
            c = decode_attention_cost([1024] * B, page, Hq, Hkv, D,
                                      quantize_kv=qkv)
            out[f"decode_b{B}{'_fp8' if qkv else ''}"] = c
    return out


def gemm_matrix(qtypes, Ms=(1, 128, 512, 2048), K: int = 4096,
                O: int = 4096) -> dict:
    """The bench.py analytic sweep: every fused format at decode and
    prefill shapes. Pure host math — lands a number with the tunnel
    down."""
    out = {}
    for qt in qtypes:
        spec = resolve_qtype(qt)
        if K % (spec.superblock or spec.block_size):
            continue
        for m in Ms:
            c = qmatmul_cost(qt, m, K, O)
            out[f"{qt}_m{m}"] = c
    return out


# ---------------------------------------------------------------------------
# quantized ICI collectives (parallel/qcollectives.py): bytes on the
# interconnect per algorithm x payload format. `ici_gbps` is the
# calibration knob twin of sim/cost.py's `hbm_gbps` — the achievable
# per-chip ring bandwidth the next live-TPU window tunes against
# measured hop times.
# ---------------------------------------------------------------------------

_SCALE_BPE = 2  # f16 per-block absmax scales (the codec's sidecar)
_COMM_BLOCK = 256  # qcollectives.DEFAULT_BLOCK (kept in sync by test)


def collective_payload_bytes(n_elems: int, comm_qtype: str = "none",
                             block_size: int = _COMM_BLOCK) -> int:
    """Wire bytes of one encoded payload of `n_elems` fp32 values:
    fp32 as-is for "none", 1 byte/elem + one f16 scale per block for
    the int8 and fp8_e4m3 codecs (identical wire size — fp8 trades
    precision for range, not bytes)."""
    if comm_qtype == "none":
        return n_elems * 4
    if comm_qtype in ("int8", "fp8_e4m3"):
        blocks = -(-n_elems // block_size)
        return n_elems + blocks * _SCALE_BPE
    raise ValueError(
        f"unknown comm_qtype {comm_qtype!r}; expected none|int8|fp8_e4m3"
    )


def all_reduce_cost(n_elems: int, axis_size: int,
                    comm_qtype: str = "none",
                    block_size: int = _COMM_BLOCK,
                    ici_gbps=None) -> dict:
    """Ring all-reduce of `n_elems` over an `axis_size` ring:
    reduce-scatter (n-1 hops) + all-gather (n-1 hops), each hop moving
    one 1/n chunk — per-device ICI bytes = 2*(n-1)/n * payload. The
    quantized ring sends codes+scales on every hop (the error-feedback
    residual stays device-local, costing nothing on the wire)."""
    n = max(int(axis_size), 1)
    payload = collective_payload_bytes(n_elems, comm_qtype, block_size)
    fp32 = collective_payload_bytes(n_elems, "none")
    ici = 2 * (n - 1) * payload / n
    out = {
        "algorithm": "ring_all_reduce", "qtype": comm_qtype,
        "axis_size": n, "elems": n_elems,
        "payload_bytes": payload,
        "ici_bytes_per_device": round(ici, 1),
        "bytes_ratio_vs_fp32": round(fp32 / max(payload, 1), 3),
    }
    if ici_gbps:
        out["ring_time_s"] = ici / (float(ici_gbps) * 1e9)
    return out


def all_gather_cost(n_elems_local: int, axis_size: int,
                    comm_qtype: str = "none",
                    block_size: int = _COMM_BLOCK,
                    ici_gbps=None) -> dict:
    """Ring all-gather of an `n_elems_local` shard over `axis_size`
    ranks: each shard's payload is encoded ONCE and forwarded n-1 hops
    (per-device ICI bytes = (n-1) * payload) — PP/multihost weight and
    KV-page distribution (sharding.gather_array)."""
    n = max(int(axis_size), 1)
    payload = collective_payload_bytes(n_elems_local, comm_qtype,
                                       block_size)
    fp32 = collective_payload_bytes(n_elems_local, "none")
    ici = (n - 1) * payload
    out = {
        "algorithm": "ring_all_gather", "qtype": comm_qtype,
        "axis_size": n, "elems_local": n_elems_local,
        "payload_bytes": payload,
        "ici_bytes_per_device": ici,
        "bytes_ratio_vs_fp32": round(fp32 / max(payload, 1), 3),
    }
    if ici_gbps:
        out["ring_time_s"] = ici / (float(ici_gbps) * 1e9)
    return out


def collective_matrix(hidden: int = 4096, layers: int = 32, tp: int = 4,
                      ici_gbps: float = 45.0, Ms=(1, 8, 32)) -> dict:
    """bench.py's analytic collective sweep at llama2-7b decode shapes:
    the per-layer TP all-reduce (o-proj + down-proj epilogues, M rows x
    hidden) at fp32 vs int8 vs fp8_e4m3, with the modeled per-decode-
    step ring time at `ici_gbps`. Pure host math — the dead-tunnel-day
    collective-bytes evidence ISSUE 17 banks."""
    out = {}
    for m in Ms:
        for qt in ("none", "int8", "fp8_e4m3"):
            c = all_reduce_cost(m * hidden, tp, qt, ici_gbps=ici_gbps)
            # 2 row-parallel epilogues per layer (wo, w_down)
            c["per_step_s"] = 2 * layers * c["ring_time_s"]
            tag = "fp32" if qt == "none" else qt
            out[f"allreduce_tp{tp}_m{m}_{tag}"] = c
    return out
