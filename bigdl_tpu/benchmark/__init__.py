"""Benchmark reporting utilities (installed with the package).

The config-driven all-in-one runner lives at the repo root
(benchmark/run.py, mirroring the reference's dev/benchmark/all-in-one);
this subpackage holds the pieces a pip-installed deployment needs —
CSV -> HTML rendering and the perf-regression gate (report.py).
"""

from bigdl_tpu.benchmark.report import check_regressions, csv_to_html  # noqa: F401
