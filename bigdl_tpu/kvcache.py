"""KV-cache subsystem.

TPU-native re-design of the reference's cache classes
(transformers/kv.py: `DynamicNormalCache` block-preallocated cache,
`DynamicFp8Cache` FP8-quantized cache, `DynamicCompressCache` /
`DynamicCompressFp8Cache` SnapKV compression). Under XLA everything is
static-shape: the cache is preallocated at `max_len` (the reference's
KV_CACHE_ALLOC_BLOCK_LENGTH growth policy becomes bucketed prefill
lengths + a fixed decode budget), lives in the jit carry, and is updated
with `lax.dynamic_update_slice` which XLA performs in place when the
buffer is donated.

Batch rows are **left-padded**: `start[b]` marks the first valid slot so
attention masks and rotary positions are exact per row.

FP8 mode stores k/v as float8_e5m2 with one float16 scale per (token,
head) vector — the same granularity as the reference's
`xe_addons.quantize_key_value` (kv.py:32-77) — halving cache HBM and
doubling effective context length.

SnapKV compression (`compress`, reference kv.py:171-245): after prefill,
the last `window` queries score every earlier key; scores are
average-pooled and the top `budget - window` slots per kv head are kept
(plus the observation window), producing a compact cache for decode.
Because keys are stored rotated, compressed slots no longer equal rope
positions — `rope_base` carries each row's true next rope position.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

_FP8_MAX = 57344.0  # float8_e5m2 finite max
_NEG_INF = -1e30


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jax.Array  # [L, B, S, Hkv, D] cache dtype (bf16 or fp8_e5m2)
    v: jax.Array
    k_scale: Optional[jax.Array]  # [L, B, S, Hkv] f16 when quantized, else None
    v_scale: Optional[jax.Array]
    # next write slot: scalar int32 (rows aligned — generate path) or [B]
    # int32 (per-row — the serving engine's continuous batching, where each
    # slot's sequence has its own length; decode writes become scatters)
    pos: jax.Array
    start: jax.Array  # [B] int32: first valid slot per row (left padding)
    # [B] int32 rope position of the token written at slot `pos`, when it
    # differs from (pos - start) — i.e. after SnapKV compression. None =
    # derived (pos - start).
    rope_base: Optional[jax.Array] = None

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def next_positions(self, t: int) -> jax.Array:
        """[B, T] rope positions for the next t tokens.

        Derived case: position of slot s is max(s - start, 0) — the clamp
        must apply per slot (not to a per-row base) so that left-padded
        prefill rows get positions 0..len-1 for their real tokens and the
        later decode positions continue them exactly."""
        step = jnp.arange(t, dtype=jnp.int32)[None, :]
        if self.rope_base is not None:
            return self.rope_base[:, None] + step
        pos = self.pos[:, None] if self.pos.ndim == 1 else self.pos
        return jnp.maximum(pos + step - self.start[:, None], 0)


def init_cache(
    n_layers: int,
    batch: int,
    max_len: int,
    n_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quantize_kv: bool = False,
) -> KVCache:
    shape = (n_layers, batch, max_len, n_kv_heads, head_dim)
    if quantize_kv:
        k = jnp.zeros(shape, jnp.float8_e5m2)
        v = jnp.zeros(shape, jnp.float8_e5m2)
        ks = jnp.zeros(shape[:-1], jnp.float16)
        vs = jnp.zeros(shape[:-1], jnp.float16)
    else:
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        ks = vs = None
    return KVCache(
        k=k, v=v, k_scale=ks, v_scale=vs,
        pos=jnp.zeros((), jnp.int32),
        start=jnp.zeros((batch,), jnp.int32),
    )


def insert_row(cache: KVCache, pcache: KVCache, slot, pad) -> KVCache:
    """Copy a 1-row prefill cache into row `slot` of a per-row-pos pool:
    k/v (and fp8 scales when quantized) land at slots [0, bucket); the
    row's pos/start become (bucket, pad). Shared by the serving engine's
    dense insert and the family engine_insert adapters (yuan/mllama)."""
    import dataclasses

    bucket = pcache.k.shape[2]
    upd = dict(
        k=jax.lax.dynamic_update_slice(cache.k, pcache.k, (0, slot, 0, 0, 0)),
        v=jax.lax.dynamic_update_slice(cache.v, pcache.v, (0, slot, 0, 0, 0)),
        pos=cache.pos.at[slot].set(bucket),
        start=cache.start.at[slot].set(pad),
    )
    if cache.k_scale is not None:
        upd["k_scale"] = jax.lax.dynamic_update_slice(
            cache.k_scale, pcache.k_scale, (0, slot, 0, 0)
        )
        upd["v_scale"] = jax.lax.dynamic_update_slice(
            cache.v_scale, pcache.v_scale, (0, slot, 0, 0)
        )
    return dataclasses.replace(cache, **upd)


def swap_out_row(cache: KVCache, slot: int, n: Optional[int] = None):
    """Copy one pool row's KV (every layer, first `n` slots — the row's
    live region; None = full row) to host RAM — the dense-engine half of
    serving preemption (the paged twin is kvpaged.swap_out_pages).
    Returns (k, v, k_scale|None, v_scale|None) numpy arrays;
    byte-preserving, so swap-in + decode is bit-exact. Slots past pos
    are never read (attention masks them; decode overwrites at pos), so
    the caller passes n >= pos to skip transferring the idle tail."""
    import numpy as np

    sl = slice(None) if n is None else slice(0, n)
    k = np.asarray(jax.device_get(cache.k[:, slot, sl]))
    v = np.asarray(jax.device_get(cache.v[:, slot, sl]))
    ks = vs = None
    if cache.quantized:
        ks = np.asarray(jax.device_get(cache.k_scale[:, slot, sl]))
        vs = np.asarray(jax.device_get(cache.v_scale[:, slot, sl]))
    return k, v, ks, vs


def swap_in_row(cache: KVCache, k, v, k_scale, v_scale, slot, pos,
                start) -> KVCache:
    """Write a swapped-out row blob back into the first k.shape[1] slots
    of row `slot` (need not be the row it came from; the stale tail
    beyond the blob is masked exactly like the tail insert_row leaves)
    and restore the row's pos/start. jit-friendly with traced
    slot/pos/start — the blob length is static from the array shape, so
    one program compiles per distinct (bucketed) length; the engine
    wraps it with a donated cache so the write is in place."""
    k = jnp.asarray(k, cache.k.dtype)
    n = k.shape[1]
    upd = dict(
        k=cache.k.at[:, slot, :n].set(k),
        v=cache.v.at[:, slot, :n].set(jnp.asarray(v, cache.v.dtype)),
        pos=cache.pos.at[slot].set(pos),
        start=cache.start.at[slot].set(start),
    )
    if cache.quantized:
        upd["k_scale"] = cache.k_scale.at[:, slot, :n].set(
            jnp.asarray(k_scale, cache.k_scale.dtype))
        upd["v_scale"] = cache.v_scale.at[:, slot, :n].set(
            jnp.asarray(v_scale, cache.v_scale.dtype))
    return dataclasses.replace(cache, **upd)


def _quantize_heads(
    x: jax.Array, scale_dtype=jnp.float16
) -> tuple[jax.Array, jax.Array]:
    """[B,T,H,D] -> (fp8 codes, [B,T,H] scales); per-vector absmax.
    The paged pool stores f32 scales (its Pallas kernel has no f16
    vectors), so it asks for scale_dtype=f32 to skip the f16 round-trip."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = absmax / _FP8_MAX
    inv = jnp.where(scale == 0, 0.0, 1.0 / jnp.where(scale == 0, 1.0, scale))
    codes = (x.astype(jnp.float32) * inv[..., None]).astype(jnp.float8_e5m2)
    return codes, scale.astype(scale_dtype)


def _scatter_rows(buf: jax.Array, layer: jax.Array, pos: jax.Array,
                  val: jax.Array) -> jax.Array:
    """buf [L,B,S,...] ← val [B,T,...] at row-dependent slots pos[b]+t.
    Per-row scatter (serving engine decode, T normally 1); XLA performs it
    in place when the buffer is donated."""
    B, T = val.shape[:2]
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    cols = pos[:, None] + jnp.arange(T)[None, :]
    layer_b = jnp.broadcast_to(layer, (B, T))
    return buf.at[layer_b, rows, cols].set(val.astype(buf.dtype), mode="drop")


def update_layer(
    cache: KVCache, layer: jax.Array, k_new: jax.Array, v_new: jax.Array
) -> KVCache:
    """Write k_new/v_new [B,T,Hkv,D] into layer `layer` at cache.pos.

    Does NOT advance pos (the model advances it once per forward, after the
    layer scan). jit-safe with traced `layer` and `cache.pos`. Scalar pos
    writes one contiguous slice; per-row pos scatters row by row.
    Dispatches to the paged pool for PagedKVCache (bigdl_tpu/kvpaged.py).
    """
    from bigdl_tpu import kvpaged

    if isinstance(cache, kvpaged.PagedKVCache):
        return kvpaged.update_layer(cache, layer, k_new, v_new)
    per_row = cache.pos.ndim == 1
    if cache.quantized:
        kq, ks = _quantize_heads(k_new)
        vq, vs = _quantize_heads(v_new)
        if per_row:
            k = _scatter_rows(cache.k, layer, cache.pos, kq)
            v = _scatter_rows(cache.v, layer, cache.pos, vq)
            k_scale = _scatter_rows(cache.k_scale, layer, cache.pos, ks)
            v_scale = _scatter_rows(cache.v_scale, layer, cache.pos, vs)
        else:
            idx = (layer, 0, cache.pos, 0, 0)
            k = jax.lax.dynamic_update_slice(cache.k, kq[None], idx)
            v = jax.lax.dynamic_update_slice(cache.v, vq[None], idx)
            k_scale = jax.lax.dynamic_update_slice(
                cache.k_scale, ks[None], (layer, 0, cache.pos, 0)
            )
            v_scale = jax.lax.dynamic_update_slice(
                cache.v_scale, vs[None], (layer, 0, cache.pos, 0)
            )
        return dataclasses.replace(cache, k=k, v=v, k_scale=k_scale, v_scale=v_scale)
    if per_row:
        k = _scatter_rows(cache.k, layer, cache.pos, k_new)
        v = _scatter_rows(cache.v, layer, cache.pos, v_new)
    else:
        idx = (layer, 0, cache.pos, 0, 0)
        k = jax.lax.dynamic_update_slice(
            cache.k, k_new[None].astype(cache.k.dtype), idx
        )
        v = jax.lax.dynamic_update_slice(
            cache.v, v_new[None].astype(cache.v.dtype), idx
        )
    return dataclasses.replace(cache, k=k, v=v)


def read_layer(
    cache: KVCache, layer: jax.Array, dtype=jnp.bfloat16
) -> tuple[jax.Array, jax.Array]:
    """Full [B,S,Hkv,D] k/v for one layer, dequantized to `dtype`."""
    from bigdl_tpu import kvpaged

    if isinstance(cache, kvpaged.PagedKVCache):
        return kvpaged.read_layer(cache, layer, dtype)
    k = jax.lax.dynamic_index_in_dim(cache.k, layer, axis=0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(cache.v, layer, axis=0, keepdims=False)
    if cache.quantized:
        ks = jax.lax.dynamic_index_in_dim(cache.k_scale, layer, 0, keepdims=False)
        vs = jax.lax.dynamic_index_in_dim(cache.v_scale, layer, 0, keepdims=False)
        k = k.astype(jnp.float32) * ks.astype(jnp.float32)[..., None]
        v = v.astype(jnp.float32) * vs.astype(jnp.float32)[..., None]
    return k.astype(dtype), v.astype(dtype)


def read_layer_raw(
    cache: KVCache, layer: jax.Array
) -> tuple[jax.Array, jax.Array, Optional[jax.Array], Optional[jax.Array]]:
    """One layer's k/v WITHOUT dequantization: ([B,S,Hkv,D] codes,
    [B,S,Hkv] f16 scales or None). The flash kernel dequantizes fp8
    blocks in-kernel (the paged path's fp8 story) — going through
    read_layer instead would materialize the full dense bf16 cache in
    HBM each step, forfeiting exactly the bytes fp8 KV saves (the dense
    `sdp_fp8` caveat, VERDICT §2.1)."""
    k = jax.lax.dynamic_index_in_dim(cache.k, layer, axis=0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(cache.v, layer, axis=0, keepdims=False)
    if not cache.quantized:
        return k, v, None, None
    ks = jax.lax.dynamic_index_in_dim(cache.k_scale, layer, 0, keepdims=False)
    vs = jax.lax.dynamic_index_in_dim(cache.v_scale, layer, 0, keepdims=False)
    return k, v, ks, vs


def advance(cache: KVCache, n: int) -> KVCache:
    rope_base = cache.rope_base
    if rope_base is not None:
        rope_base = rope_base + n
    return dataclasses.replace(cache, pos=cache.pos + n, rope_base=rope_base)


# ---------------------------------------------------------------------------
# SnapKV-style compression (reference kv.py:171-375)
# ---------------------------------------------------------------------------

def _avg_pool_1d(x: jax.Array, kernel: int) -> jax.Array:
    """Mean pool with 'same' padding over the last axis (SnapKV smoothing;
    the reference uses F.avg_pool1d on the summed score vector)."""
    if kernel <= 1:
        return x
    pad = kernel // 2
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1,) * (x.ndim - 1) + (kernel,),
        (1,) * x.ndim, [(0, 0)] * (x.ndim - 1) + [(pad, kernel - 1 - pad)],
    )
    return summed / kernel


def compress(
    cache: KVCache,
    q_obs: jax.Array,  # [L, B, W, Hq, D]: last-window queries per layer
    budget: int,
    out_len: int,
    window: int = 32,
    kernel: int = 7,
) -> KVCache:
    """SnapKV: keep, per kv head, the `budget - window` highest-attention
    prefix slots plus the `window` observation slots; write them compacted
    into a fresh cache of length `out_len` (budget + decode headroom).

    Equivalent of the reference's `compress_kv` (kv.py:171-245): softmax
    scores of the observation-window queries over the prefix, summed over
    the window and the query group, average-pooled, top-k per kv head.
    Selection is per kv head (head h's kept slots differ from head h'),
    which is fine because attention reads heads independently; the
    per-row validity boundary `start` is head-independent.

    Returns a cache with pos=budget, start = budget - kept(b), and
    rope_base = the row's true next rope position (slot indices no longer
    encode positions).
    """
    L, B, S, Hkv, D = cache.k.shape
    W = q_obs.shape[2]
    Hq = q_obs.shape[3]
    G = Hq // Hkv
    keep_k = budget - W
    assert keep_k > 0, "budget must exceed the observation window"
    assert cache.pos.ndim == 0, "compress expects an aligned (scalar-pos) cache"

    P = cache.pos  # prompt end (next slot)
    start = cache.start
    scale = 1.0 / (D ** 0.5)

    sj = jnp.arange(S)
    obs_start = P - W
    # prefix slots only: valid rows of the prompt, before the obs window.
    # Causal masking within the obs window is irrelevant: all prefix slots
    # precede every obs query.
    prefix = (sj[None, :] >= start[:, None]) & (sj[None, :] < obs_start)  # [B,S]

    def one_layer(xs):
        """Score, select, and compact a single layer — mapped over L so the
        fp32 transients ([B,Hkv,G,W,S] scores + dequantized K) stay at 1/L
        of the whole-cache footprint (the long-prompt regime this feature
        targets; the reference also compresses layer by layer)."""
        k_l, v_l, ks_l, vs_l, q_l = xs
        if ks_l is not None:
            kf = k_l.astype(jnp.float32) * ks_l.astype(jnp.float32)[..., None]
        else:
            kf = k_l.astype(jnp.float32)
        qg = q_l.astype(jnp.float32).reshape(B, W, Hkv, G, D)
        scores = jnp.einsum("bwhgd,bshd->bhgws", qg, kf) * scale
        scores = jnp.where(prefix[:, None, None, None, :], scores, _NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        # zero fully-masked rows (softmax of all -inf ~ uniform garbage)
        probs = jnp.where(prefix[:, None, None, None, :], probs, 0.0)
        vote = probs.sum(axis=(2, 3))  # [B,Hkv,S] summed over group+window
        vote = _avg_pool_1d(vote, kernel)
        vote = jnp.where(prefix[:, None, :], vote, _NEG_INF)

        _, idx = jax.lax.top_k(vote, keep_k)  # [B,Hkv,keep_k]
        valid_sel = jnp.take_along_axis(
            jnp.broadcast_to(prefix[:, None, :], vote.shape), idx, axis=-1
        )
        # temporal order with invalid slots pushed left (they land in the
        # pad region delimited by the new start)
        order_key = jnp.where(valid_sel, idx, -1)
        perm = jnp.argsort(order_key, axis=-1)
        idx_sorted = jnp.take_along_axis(idx, perm, axis=-1)

        def compact(x):  # x [B,S,Hkv,*feat]
            xt = jnp.moveaxis(x, 2, 1)  # [B,Hkv,S,*]
            expand = idx_sorted.reshape(idx_sorted.shape + (1,) * (xt.ndim - 3))
            sel = jnp.take_along_axis(
                xt,
                jnp.broadcast_to(expand, idx_sorted.shape + xt.shape[3:]),
                axis=2,
            )
            sel = jnp.moveaxis(sel, 1, 2)  # [B,keep_k,Hkv,*]
            obs = jax.lax.dynamic_slice_in_dim(x, obs_start, W, axis=1)
            merged = jnp.concatenate([sel, obs], axis=1)  # [B,budget,Hkv,*]
            pad = [(0, 0)] * x.ndim
            pad[1] = (0, out_len - budget)
            return jnp.pad(merged, pad)

        return (
            compact(k_l),
            compact(v_l),
            compact(ks_l) if ks_l is not None else None,
            compact(vs_l) if vs_l is not None else None,
        )

    if cache.quantized:
        new_k, new_v, new_ks, new_vs = jax.lax.map(
            one_layer, (cache.k, cache.v, cache.k_scale, cache.v_scale, q_obs)
        )
    else:
        new_k, new_v = jax.lax.map(
            lambda t: one_layer((t[0], t[1], None, None, t[2]))[:2],
            (cache.k, cache.v, q_obs),
        )
        new_ks = new_vs = None

    avail = jnp.maximum(obs_start - start, 0)  # prefix tokens per row
    kept = jnp.minimum(avail, keep_k)
    # invalid selected slots are left-packed; rows shorter than the obs
    # window additionally carry pad slots at the FRONT of the obs region
    # (pads are the leftmost slots), so they extend the same contiguous
    # invalid region past keep_k.
    pad_in_obs = jnp.maximum(start - obs_start, 0)
    new_start = (keep_k - kept + pad_in_obs).astype(jnp.int32)
    rope_base = jnp.maximum(P - start, 0).astype(jnp.int32)  # next position

    return KVCache(
        k=new_k, v=new_v, k_scale=new_ks, v_scale=new_vs,
        pos=jnp.asarray(budget, jnp.int32), start=new_start,
        rope_base=rope_base,
    )
