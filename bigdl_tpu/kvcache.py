"""KV-cache subsystem.

TPU-native re-design of the reference's cache classes
(transformers/kv.py: `DynamicNormalCache` block-preallocated cache,
`DynamicFp8Cache` FP8-quantized cache, `DynamicCompressCache` SnapKV
compression). Under XLA everything is static-shape: the cache is
preallocated at `max_len` (the reference's KV_CACHE_ALLOC_BLOCK_LENGTH
growth policy becomes bucketed prefill lengths + a fixed decode budget),
lives in the jit carry, and is updated with `lax.dynamic_update_slice`
which XLA performs in place when the buffer is donated.

Batch rows are **left-padded**: `start[b]` marks the first valid slot so
attention masks and rotary positions are exact per row.

FP8 mode stores k/v as float8_e5m2 with one float16 scale per (token,
head) vector — the same granularity as the reference's
`xe_addons.quantize_key_value` (kv.py:32-77) — halving cache HBM and
doubling effective context length.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

_FP8_MAX = 57344.0  # float8_e5m2 finite max


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    k: jax.Array  # [L, B, S, Hkv, D] cache dtype (bf16 or fp8_e5m2)
    v: jax.Array
    k_scale: Optional[jax.Array]  # [L, B, S, Hkv] f16 when quantized, else None
    v_scale: Optional[jax.Array]
    pos: jax.Array  # scalar int32: next write slot (shared across batch)
    start: jax.Array  # [B] int32: first valid slot per row (left padding)

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_cache(
    n_layers: int,
    batch: int,
    max_len: int,
    n_kv_heads: int,
    head_dim: int,
    dtype=jnp.bfloat16,
    quantize_kv: bool = False,
) -> KVCache:
    shape = (n_layers, batch, max_len, n_kv_heads, head_dim)
    if quantize_kv:
        k = jnp.zeros(shape, jnp.float8_e5m2)
        v = jnp.zeros(shape, jnp.float8_e5m2)
        ks = jnp.zeros(shape[:-1], jnp.float16)
        vs = jnp.zeros(shape[:-1], jnp.float16)
    else:
        k = jnp.zeros(shape, dtype)
        v = jnp.zeros(shape, dtype)
        ks = vs = None
    return KVCache(
        k=k, v=v, k_scale=ks, v_scale=vs,
        pos=jnp.zeros((), jnp.int32),
        start=jnp.zeros((batch,), jnp.int32),
    )


def _quantize_heads(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """[B,T,H,D] -> (fp8 codes, [B,T,H] f16 scales); per-vector absmax."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = absmax / _FP8_MAX
    inv = jnp.where(scale == 0, 0.0, 1.0 / jnp.where(scale == 0, 1.0, scale))
    codes = (x.astype(jnp.float32) * inv[..., None]).astype(jnp.float8_e5m2)
    return codes, scale.astype(jnp.float16)


def update_layer(
    cache: KVCache, layer: jax.Array, k_new: jax.Array, v_new: jax.Array
) -> KVCache:
    """Write k_new/v_new [B,T,Hkv,D] into layer `layer` at cache.pos.

    Does NOT advance pos (the model advances it once per forward, after the
    layer scan). jit-safe with traced `layer` and `cache.pos`.
    """
    idx = (layer, 0, cache.pos, 0, 0)
    if cache.quantized:
        kq, ks = _quantize_heads(k_new)
        vq, vs = _quantize_heads(v_new)
        k = jax.lax.dynamic_update_slice(cache.k, kq[None], idx)
        v = jax.lax.dynamic_update_slice(cache.v, vq[None], idx)
        k_scale = jax.lax.dynamic_update_slice(
            cache.k_scale, ks[None], (layer, 0, cache.pos, 0)
        )
        v_scale = jax.lax.dynamic_update_slice(
            cache.v_scale, vs[None], (layer, 0, cache.pos, 0)
        )
        return dataclasses.replace(cache, k=k, v=v, k_scale=k_scale, v_scale=v_scale)
    k = jax.lax.dynamic_update_slice(cache.k, k_new[None].astype(cache.k.dtype), idx)
    v = jax.lax.dynamic_update_slice(cache.v, v_new[None].astype(cache.v.dtype), idx)
    return dataclasses.replace(cache, k=k, v=v)


def read_layer(
    cache: KVCache, layer: jax.Array, dtype=jnp.bfloat16
) -> tuple[jax.Array, jax.Array]:
    """Full [B,S,Hkv,D] k/v for one layer, dequantized to `dtype`."""
    k = jax.lax.dynamic_index_in_dim(cache.k, layer, axis=0, keepdims=False)
    v = jax.lax.dynamic_index_in_dim(cache.v, layer, axis=0, keepdims=False)
    if cache.quantized:
        ks = jax.lax.dynamic_index_in_dim(cache.k_scale, layer, 0, keepdims=False)
        vs = jax.lax.dynamic_index_in_dim(cache.v_scale, layer, 0, keepdims=False)
        k = k.astype(jnp.float32) * ks.astype(jnp.float32)[..., None]
        v = v.astype(jnp.float32) * vs.astype(jnp.float32)[..., None]
    return k.astype(dtype), v.astype(dtype)


def advance(cache: KVCache, n: int) -> KVCache:
    return dataclasses.replace(cache, pos=cache.pos + n)
