"""Self-speculative decoding, fully on-device.

Reference algorithm (`speculative.py:803` in /root/reference): draft K
tokens autoregressively with a sym_int4 copy of the model, verify all of
them with one target forward, accept the longest matching prefix plus
one bonus token. The reference runs this as a host Python loop over
eager kernels; here the whole draft→verify→accept round is one XLA
program iterated by `lax.while_loop`, so the accept bookkeeping costs
nothing on host.

Cache discipline (static-shape version of the reference's
`_crop_past_key_values`, speculative.py:478): acceptance is capped at
K-1 drafts so that after every round
    target.pos = draft.pos = P + n_acc + 1
with all entries below pos written with the true token sequence —
"cropping" is just resetting `pos`, since slots above it are
overwritten before they can be attended.

Emitted tokens are always the TARGET's choices, so greedy speculative
output is bit-identical to greedy `generate_tokens` regardless of draft
quality — that invariant is the correctness test.

Batch size 1 (like the reference's speculative path): per-row accept
counts would need per-row cache positions.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import kvcache
from bigdl_tpu.generate import GenerationConfig, sample_token
from bigdl_tpu.models.config import ModelConfig


def _emit(out, choice, n_acc, n_gen, max_k):
    """out[0, n_gen + t] = choice[0, t] for t <= n_acc (K static)."""
    def body(t, out):
        val = jax.lax.dynamic_slice(choice, (0, t), (1, 1))
        upd = jax.lax.dynamic_update_slice(out, val, (0, n_gen + t))
        return jnp.where(t <= n_acc, upd, out)

    return jax.lax.fori_loop(0, max_k, body, out)


# auto_th_stop_draft update constants — the reference's auto_parameters
# defaults (speculative.py:810: [1, 0.5, 0.9, 1e-2, 0.9]): update every
# round, matchness EMA 0.5, target matchness 0.9, threshold step 1e-2,
# threshold EMA 0.9.
_AUTO_EMA, _AUTO_TARGET, _AUTO_STEP, _AUTO_TH_EMA = 0.5, 0.9, 1e-2, 0.9


def rejection_accept(
    key: jax.Array,
    probs: jax.Array,  # [B, K, V] target sampling distributions (filtered)
    drafts: jax.Array,  # [B, K] greedy draft tokens (one-hot proposal q)
    greedy: jax.Array,  # [B, K] target argmax tokens
    row_greedy: jax.Array,  # [B] greedy rows: argmax-match acceptance
    row_sampled: jax.Array,  # [B] sampling rows: rejection acceptance
) -> tuple[jax.Array, jax.Array]:
    """Speculative-sampling acceptance (Leviathan et al.) for a batched
    verify round, vectorized over rows with mixed decode modes.

    The draft proposes greedily, i.e. q = one-hot(d_i): draft token d_i
    is accepted with probability p_i(d_i), and on rejection the residual
    distribution max(p - q, 0)/Z reduces to p with d_i's mass zeroed,
    renormalized — so every emitted token is an EXACT sample from its
    p_i, same output law as plain sampling. Greedy rows keep the
    deterministic argmax-match rule (byte-identical to non-speculative
    serving); rows in neither mask (repetition-penalty rows, whose p
    depends on tokens emitted earlier in the same round) accept 0.

    Returns (n_acc [B], extra [B] — the token at position n_acc; the
    caller emits drafts[:, :n_acc] then extra)."""
    B, K, V = probs.shape
    k_u, k_res = jax.random.split(key)

    u = jax.random.uniform(k_u, (B, K - 1))
    p_draft = jnp.take_along_axis(
        probs[:, : K - 1], drafts[:, : K - 1, None], axis=-1
    )[..., 0]  # [B, K-1]
    acc_sampled = u < p_draft
    acc_greedy = drafts[:, : K - 1] == greedy[:, : K - 1]
    acc = jnp.where(row_greedy[:, None], acc_greedy, acc_sampled)
    acc = acc & (row_greedy | row_sampled)[:, None]
    n_acc = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)

    # the (n_acc+1)-th emitted token: on rejection at position n_acc,
    # resample from p with the rejected draft's mass removed; when all
    # K-1 drafts were accepted this is the bonus sample from p_{K-1}
    p_n = jnp.take_along_axis(probs, n_acc[:, None, None], axis=1)[:, 0]
    d_n = jnp.take_along_axis(
        drafts, jnp.minimum(n_acc, K - 1)[:, None], axis=1
    )[:, 0]
    rejected = n_acc < (K - 1)
    p_adj = jnp.where(
        rejected[:, None],
        p_n * (1.0 - jax.nn.one_hot(d_n, V, dtype=probs.dtype)),
        p_n,
    )
    extra_sampled = jax.random.categorical(
        k_res, jnp.log(p_adj + 1e-20), axis=-1
    ).astype(jnp.int32)
    extra_greedy = jnp.take_along_axis(greedy, n_acc[:, None], axis=1)[:, 0]
    extra = jnp.where(row_sampled, extra_sampled, extra_greedy)
    return n_acc, extra


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "gen", "model_forward", "cache_len", "draft_k",
        "quantize_kv", "adaptive", "min_step_draft",
    ),
)
def speculative_tokens(
    config: ModelConfig,
    target_params,
    draft_params,
    tokens: jax.Array,  # [1, T] left-padded prompt
    start: jax.Array,  # [1]
    key: jax.Array,
    gen: GenerationConfig,
    model_forward,
    cache_len: int,
    draft_k: int = 4,
    quantize_kv: bool = False,
    adaptive: bool = True,
    th_stop_draft: float = 0.8,
    min_step_draft: int = 3,
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Returns (out [1, max_new_tokens], n_rounds, n_drafted, n_matched).

    adaptive=True is the reference's th_stop_draft mechanism
    (speculative.py:827-1269): drafting early-stops once the draft's
    confidence (its greedy token probability) drops below a threshold —
    a dynamic-trip-count while_loop, so unproductive draft forwards are
    genuinely skipped — and the threshold itself tracks an EMA of the
    acceptance rate: low matchness raises it (stop drafting sooner),
    saturated drafting lowers it. The threshold rides the decode loop as
    a traced scalar; verify stays a static-K forward with acceptance
    capped at the drafted count.
    """
    B, T = tokens.shape
    assert B == 1, "speculative decoding is batch-1 (same as the reference)"
    K = draft_k
    max_new = gen.max_new_tokens
    slack = max_new + K + 1
    assert cache_len >= T + slack

    def new_cache():
        c = kvcache.init_cache(
            config.num_hidden_layers, B, cache_len, config.num_key_value_heads,
            config.head_dim_, quantize_kv=quantize_kv,
        )
        return dataclasses.replace(c, start=start)

    tcache, dcache = new_cache(), new_cache()

    # Prefill both models on the prompt; first token comes from the target.
    tlogits, tcache = model_forward(config, target_params, tokens, tcache, mode="prefill")
    _, dcache = model_forward(config, draft_params, tokens, dcache, mode="prefill")
    key, k0 = jax.random.split(key)
    cur = sample_token(tlogits[:, -1], k0, gen)  # [1]

    out = jnp.full((B, slack), gen.pad_token_id, jnp.int32)
    out = out.at[:, 0].set(cur)
    eos = gen.eos_token_id
    done = cur == eos if eos is not None else jnp.zeros((B,), jnp.bool_)

    def cond(state):
        return (state["n_gen"] < max_new) & ~jnp.all(state["done"])

    def round_fn(state):
        n_gen, cur, key = state["n_gen"], state["cur"], state["key"]
        tcache, dcache = state["tcache"], state["dcache"]
        th, out = state["th"], state["out"]

        # --- draft up to K tokens greedily, early-stopping on confidence
        # (writes n_draft KV entries: cur, d0..d_{n_draft-2})
        def draft_cond(carry):
            i, _, _, _, go = carry
            return (i < K) & go

        def draft_step(carry):
            i, tok, dcache, drafts, _ = carry
            logits, dcache = model_forward(
                config, draft_params, tok[:, None], dcache, mode="decode"
            )
            probs = jax.nn.softmax(logits[:, -1].astype(jnp.float32), axis=-1)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            conf = jnp.max(probs, axis=-1)[0]
            drafts = jax.lax.dynamic_update_slice(drafts, nxt[:, None], (0, i))
            # reference early-stop (speculative.py:1049): confidence below
            # threshold after min_step_draft drafts ends the phase
            # (adaptive is a static python bool — no bitwise ~ on it)
            go = jnp.asarray(not adaptive) | (conf >= th) | (i + 1 < min_step_draft)
            return (i + 1, nxt, dcache, drafts, go)

        drafts0 = jnp.zeros((B, K), jnp.int32)
        n_draft, _, dcache, drafts, _ = jax.lax.while_loop(
            draft_cond, draft_step,
            (jnp.zeros((), jnp.int32), cur, dcache, drafts0,
             jnp.ones((), jnp.bool_)),
        )

        # --- verify: one target forward over [cur, d0..d_{K-2}]  (T = K;
        # static shape — positions past n_draft carry stale drafts that the
        # acceptance cap below excludes)
        verify_in = jnp.concatenate([cur[:, None], drafts[:, : K - 1]], axis=1)
        tlogits, tcache = model_forward(
            config, target_params, verify_in, tcache, mode="prefill"
        )
        key, kk = jax.random.split(key)
        keys = jax.random.split(kk, K)
        choice = jnp.stack(
            [sample_token(tlogits[:, i], keys[i], gen) for i in range(K)], axis=1
        )  # [1, K] target's token for each position

        # --- longest matching prefix, capped at K-1 AND n_draft-1: the
        # draft cache only holds KV for cur, d0..d_{n_draft-2}, so
        # accepting d_{n_draft-1} would advance past a never-written slot
        # and corrupt every later draft prediction
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, K - 1), 1)
        match = (drafts[:, : K - 1] == choice[:, : K - 1]) & (idx < n_draft - 1)
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)[0]

        out = _emit(out, choice, n_acc, n_gen, K)
        cur = jax.lax.dynamic_slice(choice, (0, n_acc), (1, 1))[:, 0]

        # crop both caches to the accepted length (true pos = old + n_acc+1;
        # the draft cache advanced n_draft, the target K)
        new_pos = tcache.pos - K + n_acc + 1
        tcache = dataclasses.replace(tcache, pos=new_pos)
        dcache = dataclasses.replace(dcache, pos=new_pos)

        # --- adaptive threshold (reference speculative.py:1225-1236).
        # Matchness normalizes by the ACCEPTABLE drafts (n_draft - 1, our
        # static-cache cap) rather than the raw draft count — otherwise a
        # perfect draft tops out at (K-1)/K < target and the threshold
        # ratchets upward forever, degrading drafting to min_step_draft.
        # n_draft <= 1 carries no acceptance signal (zero acceptable
        # drafts): skip the EMA update or the threshold ratchets to its
        # cap and permanently collapses drafting
        matchness = jnp.where(
            n_draft > 1,
            _AUTO_EMA * state["matchness"]
            + (1 - _AUTO_EMA) * n_acc.astype(jnp.float32)
            / jnp.maximum(n_draft.astype(jnp.float32) - 1.0, 1.0),
            state["matchness"],
        )
        new_th = jnp.where(
            matchness < _AUTO_TARGET,
            th + _AUTO_STEP,  # low acceptance: stop drafting sooner
            jnp.where(n_draft == K, th, th - _AUTO_STEP),
        )
        new_th = jnp.clip(new_th, 0.05, 0.99)
        th = jnp.where(
            adaptive & (n_draft > 1),  # no-signal rounds must not ratchet
            _AUTO_TH_EMA * th + (1 - _AUTO_TH_EMA) * new_th, th,
        )

        done = state["done"]
        if eos is not None:
            emitted = jax.lax.dynamic_slice(out, (0, n_gen), (1, K))
            kidx = jax.lax.broadcasted_iota(jnp.int32, (1, K), 1)
            done = done | jnp.any((emitted == eos) & (kidx <= n_acc), axis=1)
        return {
            "n_gen": n_gen + n_acc + 1, "cur": cur, "tcache": tcache,
            "dcache": dcache, "done": done, "out": out, "key": key,
            "n_rounds": state["n_rounds"] + 1,
            "n_drafted": state["n_drafted"] + n_draft,
            "n_matched": state["n_matched"] + n_acc,
            "th": th, "matchness": matchness,
        }

    state = {
        "n_gen": jnp.ones((), jnp.int32), "cur": cur, "tcache": tcache,
        "dcache": dcache, "done": done, "out": out, "key": key,
        "n_rounds": jnp.zeros((), jnp.int32),
        "n_drafted": jnp.zeros((), jnp.int32),
        "n_matched": jnp.zeros((), jnp.int32),
        "th": jnp.asarray(th_stop_draft, jnp.float32),
        "matchness": jnp.zeros((), jnp.float32),
    }
    state = jax.lax.while_loop(cond, round_fn, state)
    return (state["out"][:, :max_new], state["n_rounds"],
            state["n_drafted"], state["n_matched"])


def mask_after_eos(out: np.ndarray, eos: int | None, pad: int) -> np.ndarray:
    """Host-side cleanup: tokens after the first EOS become pad (rounds can
    emit a few tokens past EOS before the loop notices)."""
    if eos is None:
        return out
    out = np.array(out)
    for b in range(out.shape[0]):
        hits = np.nonzero(out[b] == eos)[0]
        if hits.size:
            out[b, hits[0] + 1:] = pad
    return out


def speculative_generate(
    config: ModelConfig,
    target_params,
    draft_params,
    prompts,
    model_forward,
    max_new_tokens: int = 32,
    draft_k: int = 4,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k=None,
    top_p=None,
    eos_token_id=None,
    pad_token_id: int = 0,
    seed: int = 0,
    quantize_kv: bool = False,
    adaptive: bool = True,
    th_stop_draft: float = 0.8,
    min_step_draft: int = 3,
) -> np.ndarray:
    """Host entry point mirroring `speculative_generate` (speculative.py:803);
    adaptive/th_stop_draft/min_step_draft mirror its th_stop_draft knobs."""
    from bigdl_tpu.generate import pad_prompts

    tokens, start = pad_prompts(prompts, pad_token_id)
    gen = GenerationConfig(
        max_new_tokens=max_new_tokens, do_sample=do_sample,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id,
    )
    from bigdl_tpu.utils import cache_len_for

    cache_len = cache_len_for(tokens.shape[1], max_new_tokens + draft_k + 1)
    out, _, _, _ = speculative_tokens(
        config, target_params, draft_params,
        jnp.asarray(tokens), jnp.asarray(start), jax.random.PRNGKey(seed),
        gen, model_forward, cache_len=cache_len, draft_k=draft_k,
        quantize_kv=quantize_kv, adaptive=adaptive,
        th_stop_draft=th_stop_draft, min_step_draft=min_step_draft,
    )
    return mask_after_eos(np.asarray(out), eos_token_id, pad_token_id)
