"""Decode-time acceleration algorithms.

TPU-native re-design of the reference's L5 layer (SURVEY.md §2.2):
- self-speculative decoding (`transformers/speculative.py:803` in
  /root/reference): low-bit draft of the same checkpoint proposes, the
  full-precision target verifies — here both run inside ONE jitted
  while_loop, no host round-trips per token;
- prompt-lookup / lookahead decoding (`transformers/lookup.py:145-457`):
  n-gram candidates from the token history verified the same way.
"""

from bigdl_tpu.decode.speculative import speculative_generate
from bigdl_tpu.decode.lookup import lookup_generate

__all__ = ["speculative_generate", "lookup_generate"]
