"""Prompt-lookup (n-gram) speculative decoding, fully on-device.

Reference: `PromptLookupCandidateGenerator` + `lookup_generate`
(lookup.py:145-457 in /root/reference) — candidate continuations come
from matching the trailing n-gram of the generated text against earlier
history (great for summarization/RAG where output quotes input), then a
single target forward verifies them. No draft model needed.

The reference scans for n-gram matches on host per token; here matching
is a vectorized compare over the (static-size) history buffer inside the
same jitted while_loop as the verify forward. Acceptance bookkeeping is
identical to bigdl_tpu.decode.speculative (cap K-1, crop = pos reset),
and emitted tokens are always the target's choices, so greedy output is
bit-identical to plain generate.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import kvcache
from bigdl_tpu.decode.speculative import _emit, mask_after_eos
from bigdl_tpu.generate import GenerationConfig, sample_token
from bigdl_tpu.models.config import ModelConfig


def _find_candidate(hist, hist_len, row_start, n: int, k: int):
    """Most recent earlier occurrence of the trailing n-gram.

    Returns (found [bool], cand [1, k]) — the k tokens following the match.
    """
    L = hist.shape[1]
    idx = jnp.arange(L)
    last = jax.lax.dynamic_slice(hist, (0, hist_len - n), (1, n))
    m = jnp.ones((L,), jnp.bool_)
    for j in range(n):  # n is static and small
        m = m & (jnp.roll(hist[0], -j) == last[0, j])
    # p must start at a real token, match inside history, and not be the
    # trailing n-gram itself; continuation must exist.
    m = m & (idx >= row_start) & (idx + n < hist_len)
    found = jnp.any(m)
    p = jnp.max(jnp.where(m, idx, -1))
    cand = jax.lax.dynamic_slice(hist, (0, jnp.maximum(p, 0) + n), (1, k))
    return found, cand


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "gen", "model_forward", "cache_len", "lookahead",
        "max_ngram", "quantize_kv",
    ),
)
def lookup_tokens(
    config: ModelConfig,
    params,
    tokens: jax.Array,  # [1, T] left-padded prompt
    start: jax.Array,  # [1]
    key: jax.Array,
    gen: GenerationConfig,
    model_forward,
    cache_len: int,
    lookahead: int = 4,
    max_ngram: int = 3,
    quantize_kv: bool = False,
) -> jax.Array:
    B, T = tokens.shape
    assert B == 1, "lookup decoding is batch-1 (same as the reference)"
    K = lookahead
    max_new = gen.max_new_tokens
    slack = max_new + K + 1
    assert cache_len >= T + slack

    cache = kvcache.init_cache(
        config.num_hidden_layers, B, cache_len, config.num_key_value_heads,
        config.head_dim_, quantize_kv=quantize_kv,
    )
    cache = dataclasses.replace(cache, start=start)

    logits, cache = model_forward(config, params, tokens, cache, mode="prefill")
    key, k0 = jax.random.split(key)
    cur = sample_token(logits[:, -1], k0, gen)

    # History buffer: prompt then generated tokens, contiguous from `start`.
    hist = jnp.zeros((B, T + slack), jnp.int32)
    hist = jax.lax.dynamic_update_slice(hist, tokens, (0, 0))
    hist = jax.lax.dynamic_update_slice(hist, cur[:, None], (0, T))
    hist_len = jnp.asarray(T + 1, jnp.int32)

    out = jnp.full((B, slack), gen.pad_token_id, jnp.int32)
    out = out.at[:, 0].set(cur)
    eos = gen.eos_token_id
    done = cur == eos if eos is not None else jnp.zeros((B,), jnp.bool_)

    def cond(state):
        n_gen = state[0]
        done = state[4]
        return (n_gen < max_new) & ~jnp.all(done)

    def round_fn(state):
        n_gen, cur, cache, hist, done, out, key, hist_len = state

        # candidate drafts from the longest matching n-gram
        drafts = jnp.zeros((B, K - 1), jnp.int32)
        found_any = jnp.zeros((), jnp.bool_)
        for n in range(max_ngram, 0, -1):  # static unroll, first hit wins
            found, cand = _find_candidate(hist, hist_len, start[0], n, K - 1)
            take = found & ~found_any
            drafts = jnp.where(take, cand, drafts)
            found_any = found_any | found

        verify_in = jnp.concatenate([cur[:, None], drafts], axis=1)  # [1, K]
        tlogits, cache = model_forward(
            config, params, verify_in, cache, mode="prefill"
        )
        key, kk = jax.random.split(key)
        keys = jax.random.split(kk, K)
        choice = jnp.stack(
            [sample_token(tlogits[:, i], keys[i], gen) for i in range(K)], axis=1
        )

        match = drafts == choice[:, : K - 1]
        n_acc = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)[0]
        # no candidate found -> plain decode step (bonus token only)
        n_acc = jnp.where(found_any, n_acc, 0)

        out = _emit(out, choice, n_acc, n_gen, K)
        hist = _emit(hist, choice, n_acc, hist_len, K)
        cur = jax.lax.dynamic_slice(choice, (0, n_acc), (1, 1))[:, 0]

        cache = dataclasses.replace(cache, pos=cache.pos - K + n_acc + 1)
        hist_len = hist_len + n_acc + 1

        if eos is not None:
            emitted = jax.lax.dynamic_slice(out, (0, n_gen), (1, K))
            idx = jax.lax.broadcasted_iota(jnp.int32, (1, K), 1)
            done = done | jnp.any((emitted == eos) & (idx <= n_acc), axis=1)
        return (n_gen + n_acc + 1, cur, cache, hist, done, out, key, hist_len)

    state = (jnp.ones((), jnp.int32), cur, cache, hist, done, out, key, hist_len)
    state = jax.lax.while_loop(cond, round_fn, state)
    out = state[5]
    return out[:, :max_new]


def lookup_generate(
    config: ModelConfig,
    params,
    prompts,
    model_forward,
    max_new_tokens: int = 32,
    lookahead: int = 4,
    max_ngram: int = 3,
    do_sample: bool = False,
    temperature: float = 1.0,
    top_k=None,
    top_p=None,
    eos_token_id=None,
    pad_token_id: int = 0,
    seed: int = 0,
    quantize_kv: bool = False,
) -> np.ndarray:
    """Host entry point mirroring `lookup_generate` (lookup.py:274)."""
    from bigdl_tpu.generate import pad_prompts

    tokens, start = pad_prompts(prompts, pad_token_id)
    gen = GenerationConfig(
        max_new_tokens=max_new_tokens, do_sample=do_sample,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id,
    )
    from bigdl_tpu.utils import cache_len_for

    cache_len = cache_len_for(tokens.shape[1], max_new_tokens + lookahead + 1)
    out = lookup_tokens(
        config, params, jnp.asarray(tokens), jnp.asarray(start),
        jax.random.PRNGKey(seed), gen, model_forward, cache_len=cache_len,
        lookahead=lookahead, max_ngram=max_ngram, quantize_kv=quantize_kv,
    )
    return mask_after_eos(np.asarray(out), eos_token_id, pad_token_id)
