"""OpenAI-compatible HTTP server over the continuous-batching engine.

Role-equivalent of the reference's lightweight FastAPI server
(`serving/fastapi/api_server.py:245-434` in /root/reference: /generate,
/generate_stream, /v1/chat/completions, /v1/completions, plus the
`ModelWorker.process_step` batching loop in model_worker.py:28-200), built
on the standard library's threading HTTP server — the runtime has zero
third-party serving dependencies; the engine thread IS the worker loop.

Endpoints:
    GET  /health                     {"status": "ok"}
    POST /generate                   {"prompt": str|[int], "max_new_tokens"}
    POST /generate_stream            same, server-sent events
    POST /v1/completions             OpenAI completion schema (subset)
    POST /v1/chat/completions        OpenAI chat schema (subset), streaming
    POST /v1/audio/transcriptions    whisper (pass whisper=(config, params));
                                     body: raw audio/wav, or JSON
                                     {"audio": [floats @ 16 kHz]}

Text prompts need a tokenizer (pass tokenizer= or a HF model_path);
token-id list prompts work without one. Transcriptions return text when
a whisper_tokenizer is set, raw token ids otherwise.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Optional

from bigdl_tpu.serving.engine import InferenceEngine


def _sampling_kwargs(payload: dict) -> dict:
    """OpenAI-ish request fields → per-request engine sampling kwargs.
    temperature<=0 means greedy (the OpenAI convention); presence of a
    positive temperature / top_p<1 / top_k>0 implies sampling unless
    do_sample is given explicitly. do_sample:true with temperature<=0 is
    contradictory and rejected (it would silently sample at the engine
    default temperature)."""
    from bigdl_tpu.utils.errors import invalid_input_error

    kw: dict = {}
    if "temperature" in payload:
        t = float(payload["temperature"])
        if t <= 0:
            invalid_input_error(
                not payload.get("do_sample"),
                "do_sample=true with temperature<=0 is contradictory; "
                "drop do_sample for greedy or set temperature>0",
            )
            kw["do_sample"] = False
        else:
            kw.update(do_sample=True, temperature=t)
    if "top_p" in payload:
        kw["top_p"] = float(payload["top_p"])  # 1.0 = explicit disable
        if kw["top_p"] < 1.0:
            kw.setdefault("do_sample", True)
    if "top_k" in payload:
        kw["top_k"] = int(payload["top_k"])  # 0 = explicit disable
        if kw["top_k"] > 0:
            kw.setdefault("do_sample", True)
    if "do_sample" in payload:
        # explicit value wins over implied sampling (the t<=0 contradiction
        # was already rejected above)
        kw["do_sample"] = bool(payload["do_sample"])
    if "repetition_penalty" in payload:
        p = float(payload["repetition_penalty"])
        # HF/TGI contract: penalty > 0 (0 divides logits to inf/NaN)
        invalid_input_error(
            p > 0, f"repetition_penalty must be > 0, got {p}"
        )
        kw["repetition_penalty"] = p
    if "eos_token_id" in payload:
        kw["eos_token_id"] = int(payload["eos_token_id"])
    if payload.get("adapter") is not None:
        # multi-tenant LoRA (docs/serving.md §7): the named adapter this
        # request decodes with; resolution/refcounting happens at engine
        # admission, so a bad name is a structured per-request error
        a = payload["adapter"]
        invalid_input_error(
            isinstance(a, str) and bool(a),
            f"adapter must be a non-empty string, got {a!r}",
        )
        kw["adapter"] = a
    for f in ("queue_deadline_s", "deadline_s"):
        # per-request overload controls (docs/serving.md): how long the
        # request may wait for a slot, and its total wall-clock budget
        if f in payload:
            try:
                v = float(payload[f])
            except (TypeError, ValueError):
                invalid_input_error(
                    False, f"{f} must be a number, got {payload[f]!r}"
                )
            invalid_input_error(v > 0, f"{f} must be > 0, got {v}")
            kw[f] = v
    return kw


class _EngineThread(threading.Thread):
    def __init__(self, engine: InferenceEngine):
        super().__init__(daemon=True)
        self.engine = engine
        self.stop_flag = threading.Event()

    def run(self):
        while not self.stop_flag.is_set():
            try:
                busy = self.engine.step()
            except Exception as e:  # noqa: BLE001
                # fail everything in flight so clients unblock, then keep
                # serving (a poisoned request must not kill the server)
                self.engine.fail_all(f"engine error: {e}")
                busy = False
            if not busy:
                time.sleep(0.002)


class ApiServer:
    def __init__(
        self,
        model,
        tokenizer=None,
        host: str = "127.0.0.1",
        port: int = 8000,
        n_slots: int = 8,
        max_len: int = 1024,
        gen=None,
        whisper=None,  # (WhisperConfig, params) enables /v1/audio/*
        whisper_tokenizer=None,
        embedder=None,  # (BertConfig, params, tokenizer): /v1/embeddings
        paged: bool = False,  # paged KV pool + radix prefix caching
        # (kvpaged.py, serving/radix.py)
        page_size: int = 64,
        n_pages=None,
        prefill_chunk_tokens=None,  # paged: bound the decode stall a
        # long arriving prompt can inflict to one chunk of this many
        # tokens (docs/serving.md §6); None = monolithic prefill
        speculative: bool = False,  # in-engine draft-K-then-verify
        draft_params=None,  # None = sym_int4 self-draft of the model
        draft_k: int = 4,
        adaptive_draft: bool = False,  # acceptance-steered draft length
        truncate_prompts: bool = False,  # opt-in: keep over-long tails
        logprobs_top_k: int = 0,  # OpenAI top_logprobs alternatives
        journal: Optional[str] = None,  # crash-recovery request journal
        request_timeout_s: float = 300.0,  # buffered-wait / stream-stall
        # budget; on expiry the request is CANCELLED in the engine (the
        # slot frees) and the client sees 504 — never a leaked slot
        max_queue: Optional[int] = None,  # engine admission bound: over-
        # capacity submits get 429 + Retry-After instead of queueing
        queue_deadline_s: Optional[float] = None,  # default max queue
        # wait; expired requests get 503 + Retry-After
        deadline_s: Optional[float] = None,  # default total budget (504)
        preemption: bool = True,  # host-RAM KV swap under page pressure
        faults=None,  # FaultInjector for chaos testing (serving/faults.py)
        adapters=None,  # AdapterRegistry (serving/adapters.py): enables
        # per-request "adapter" fields on every generate surface plus
        # the POST /adapters/{load,unload} + GET /adapters lifecycle
        # endpoints (docs/serving.md §7)
        tracing: bool = False,  # request-lifecycle span recording
        # (obs/tracing.py); the ring always exists so POST /debug/trace
        # can flip it on a live server — disabled it costs one attribute
        # check per hook
        trace_capacity: int = 65536,  # span ring-buffer bound
        request_log: Optional[str] = None,  # per-request derived-timings
        # JSONL (crc-suffixed; docs/observability.md)
        clock: Callable[[], float] = time.time,  # every server-side
        # timestamp (uptime, `created`, Retry-After rate, wait/stream/
        # drain deadlines) AND the engine + tracer it constructs flow
        # through this one injectable clock, so the simulated-clock
        # benchmark can drive the whole API layer (docs/observability.md;
        # graftlint WCT001 enforces no bare wall-clock calls here)
    ):
        from bigdl_tpu.obs.tracing import TraceRecorder
        from bigdl_tpu.serving.metrics import Metrics

        self._clock = clock
        self.tracer = TraceRecorder(capacity=trace_capacity,
                                    enabled=tracing, clock=clock)
        self.adapters = adapters
        if adapters is not None:
            # registry lifecycle events land in the same trace ring,
            # clock domain, and fault-injection table as the engine
            adapters.bind(tracer=self.tracer, clock=clock, faults=faults)
        self.engine = InferenceEngine(
            model, n_slots=n_slots, max_len=max_len, gen=gen,
            paged=paged, page_size=page_size, n_pages=n_pages,
            prefill_chunk_tokens=prefill_chunk_tokens,
            speculative=speculative, draft_params=draft_params,
            draft_k=draft_k, adaptive_draft=adaptive_draft,
            truncate_prompts=truncate_prompts,
            logprobs_top_k=logprobs_top_k, journal=journal,
            max_queue=max_queue, queue_deadline_s=queue_deadline_s,
            deadline_s=deadline_s, preemption=preemption, faults=faults,
            adapters=adapters,
            tracer=self.tracer, request_log=request_log, clock=clock,
        )
        self.request_timeout_s = request_timeout_s
        self._t_start = clock()
        self.tokenizer = tokenizer
        self.whisper = whisper
        self.whisper_tokenizer = whisper_tokenizer
        self.embedder = embedder
        self.metrics = Metrics(self.engine)
        # serializes whisper device work: handler threads must not race
        # each other (or pile unbounded compute onto the chip) the way
        # the engine thread already serializes text decode
        self._whisper_lock = threading.Lock()
        self._embed_lock = threading.Lock()
        self.worker = _EngineThread(self.engine)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json_raw(self, code: int, obj: Any, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, str(v))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, obj: Any, headers=None):
                self._status = code  # annotated for metrics
                return self._json_raw(code, obj, headers)

            def do_GET(self):
                if self.path == "/health":
                    return self._json(200, {"status": "ok"})
                if self.path == "/recovered":
                    # journal-replayed requests from a previous process:
                    # their original clients died with that process, so
                    # the results are retrievable here instead of being
                    # recomputed-and-discarded (decode happens once; the
                    # operator or a reconciliation job collects them)
                    out = []
                    for r in outer.engine.recovered_requests:
                        out.append({
                            "rid": r.rid,
                            "prompt": r.prompt,
                            "done": r.done,
                            "finish_reason": r.finish_reason,
                            "tokens": list(r.out_tokens),
                            "text": outer._decode_tok(r.out_tokens)
                            if r.done else None,
                        })
                    return self._json(200, {"recovered": out})
                if self.path == "/info":  # TGI-protocol model info
                    from bigdl_tpu import __version__

                    cfg = outer.engine.config
                    return self._json(200, {
                        "model_id": cfg.model_type,
                        "model_dtype": outer.engine.model.qtype,
                        "max_total_tokens": outer.engine.max_len,
                        "max_concurrent_requests": outer.engine.n_slots,
                        "version": __version__,
                    })
                if self.path == "/metrics":
                    body = outer.metrics.render().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return None
                if self.path == "/adapters":
                    # multi-tenant LoRA inventory (docs/serving.md §7):
                    # residency, refcounts, pin state, churn counters
                    if outer.adapters is None:
                        return self._json(
                            400, {"error": "no adapter registry (pass "
                                  "adapters= to ApiServer)"})
                    return self._json(200, {
                        "adapters": outer.adapters.resident(),
                        "stats": outer.adapters.stats(),
                    })
                if self.path == "/debug/trace":
                    # the ring buffer as Chrome trace-event JSON — saved
                    # to a file it loads directly in Perfetto
                    # (docs/observability.md; `bigdl-tpu trace dump`)
                    return self._json(200, outer.tracer.export())
                if self.path == "/debug/profiler":
                    from bigdl_tpu.obs.profiler import PROFILER

                    return self._json(200, PROFILER.status())
                return self._json(404, {"error": "not found"})

            def _debug_trace(self, payload):
                """POST /debug/trace: toggle span recording / clear the
                ring on a live server ({"enabled": bool?, "clear":
                bool?}); responds with the recorder status."""
                if "enabled" in payload:
                    outer.tracer.enabled = bool(payload["enabled"])
                if payload.get("clear"):
                    outer.tracer.clear()
                return self._json(200, outer.tracer.status())

            def _debug_profiler(self, payload):
                """POST /debug/profiler: {"action": "start", "logdir":
                ...} opens a guarded jax.profiler window; {"action":
                "stop"} closes it. Busy/idle misuse is 409, never a
                wedged profiler."""
                from bigdl_tpu.obs.profiler import (
                    PROFILER, ProfilerBusy, ProfilerIdle,
                )

                action = payload.get("action")
                try:
                    if action == "start":
                        logdir = payload.get("logdir")
                        if not logdir:
                            return self._json(
                                400, {"error": "profiler start needs "
                                      "a logdir"})
                        return self._json(200, PROFILER.start(logdir))
                    if action == "stop":
                        return self._json(200, PROFILER.stop())
                except (ProfilerBusy, ProfilerIdle) as e:
                    return self._json(409, {"error": str(e)})
                return self._json(
                    400, {"error": f"unknown profiler action "
                          f"{action!r}; use start|stop"})

            _KNOWN_POSTS = {
                "/generate", "/generate_stream", "/v1/completions",
                "/v1/chat/completions", "/v1/audio/transcriptions",
                "/v1/embeddings", "/debug/trace", "/debug/profiler",
                "/adapters/load", "/adapters/unload",
            }

            # AdapterError.kind -> HTTP status (docs/serving.md §7):
            # missing artifacts are a 404, a live-referenced unload is a
            # 409 the operator retries after drain, corrupt/mismatched
            # artifacts are an unprocessable 422, and an over-budget
            # load is a 507 (insufficient storage — literally)
            _ADAPTER_STATUS = {"missing": 404, "busy": 409,
                               "corrupt": 422, "rank_mismatch": 422,
                               "budget": 507}

            def _adapter_op(self, payload, op: str):
                """POST /adapters/{load,unload}: operator lifecycle for
                the multi-tenant registry."""
                if outer.adapters is None:
                    return self._json(
                        400, {"error": "no adapter registry (pass "
                              "adapters= to ApiServer)"})
                from bigdl_tpu.serving.adapters import AdapterError

                name = payload.get("name")
                if not isinstance(name, str) or not name:
                    return self._json(
                        400, {"error": "body needs a non-empty "
                              '"name" string'})
                try:
                    if op == "load":
                        desc = outer.adapters.load(
                            name, path=payload.get("path"),
                            pin=bool(payload.get("pin", False)),
                        )
                        # validate against the SERVING model now: an
                        # operator pre-loading a wrong-base artifact
                        # should hear 422 here, not watch every tenant
                        # request error later (the registry alone
                        # cannot see the model's dims). peek() — a
                        # validation pass must not count as a hit.
                        entry = outer.adapters.peek(name)
                        if entry is not None:
                            try:
                                outer.engine._check_adapter_dims(entry)
                            except AdapterError:
                                outer.adapters.reject(entry, held=False)
                                raise
                    else:
                        desc = outer.adapters.unload(name)
                except AdapterError as e:
                    return self._json(
                        self._ADAPTER_STATUS.get(e.kind, 400),
                        {"error": str(e), "kind": e.kind, "name": name},
                    )
                return self._json(200, {"adapter": desc, "op": op})

            def do_POST(self):
                from bigdl_tpu.utils.errors import (
                    InvalidInputError, request_timer,
                )

                self._status = 200
                # unknown paths share one metrics label — raw paths would
                # let a scanner grow the registry without bound
                label = self.path if self.path in self._KNOWN_POSTS else "other"
                with request_timer(outer.metrics, label) as timer:
                    try:
                        self._route_post()
                    except InvalidInputError as e:
                        self._json(400, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001
                        self._json(500, {"error": str(e)})
                    timer.status = self._status

            def _route_post(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    raw = self.rfile.read(n)
                except Exception as e:
                    return self._json(400, {"error": f"bad request: {e}"})
                if self.path == "/v1/audio/transcriptions":
                    return self._transcribe(raw)
                try:
                    payload = json.loads(raw or b"{}")
                except Exception as e:
                    return self._json(400, {"error": f"bad json: {e}"})
                # TGI request schema: "inputs" (parameters optional); the
                # legacy shape uses "prompt"
                is_tgi = "parameters" in payload or (
                    "inputs" in payload and "prompt" not in payload
                )
                if self.path == "/debug/trace":
                    return self._debug_trace(payload)
                if self.path == "/debug/profiler":
                    return self._debug_profiler(payload)
                if self.path == "/adapters/load":
                    return self._adapter_op(payload, "load")
                if self.path == "/adapters/unload":
                    return self._adapter_op(payload, "unload")
                if self.path == "/v1/embeddings":
                    return self._embeddings(payload)
                if self.path == "/generate":
                    if is_tgi:
                        return self._tgi_generate(payload, stream=False)
                    return self._generate(payload, stream=False)
                if self.path == "/generate_stream":
                    if is_tgi:
                        return self._tgi_generate(payload, stream=True)
                    return self._generate(payload, stream=True)
                if self.path == "/v1/completions":
                    return self._completions(payload)
                if self.path == "/v1/chat/completions":
                    return self._chat(payload)
                return self._json(404, {"error": "not found"})

            def _tgi_generate(self, payload, stream: bool):
                """text-generation-inference protocol (the reference's
                TGI-protocol worker, serving/fastchat/tgi_api_server.py):
                {"inputs": str, "parameters"?: {...}} ->
                {"generated_text": ...}. The stream variant follows the
                TGI StreamResponse shape: every event carries a token
                object and generated_text rides the LAST token event."""
                from bigdl_tpu.utils.errors import invalid_input_error

                params = payload.get("parameters") or {}
                ids = outer._encode(payload.get("inputs", ""))
                maxnt = int(params.get("max_new_tokens", 64))
                kw = _sampling_kwargs(params)
                stops = params.get("stop", []) or []
                invalid_input_error(
                    isinstance(stops, list)
                    and all(isinstance(s, str) for s in stops),
                    "parameters.stop must be a list of strings",
                )

                def cut(text):
                    for s in stops:
                        idx = text.find(s)
                        if idx >= 0:
                            return text[:idx], True
                    return text, False

                def tokens_until_cut(out_tokens):
                    """(text, finish_reason_override, n_tokens): decode
                    incrementally so generated_tokens matches the cut."""
                    pieces = []
                    for n, tok in enumerate(out_tokens, start=1):
                        pieces.append(outer._decode_tok([tok]))
                        full, hit = cut("".join(pieces))
                        if hit:
                            return full, "stop_sequence", n
                    full, _ = cut("".join(pieces))
                    return full, None, len(out_tokens)

                if not stream:
                    req = outer.engine.submit(ids, maxnt, **kw)
                    if outer._wait(req):
                        return self._timeout_504(req)
                    if req.error:
                        return self._req_error(req)
                    text, stop_reason, n_gen = tokens_until_cut(req.out_tokens)
                    body = {"generated_text": text}
                    if params.get("details"):
                        body["details"] = {
                            "finish_reason": stop_reason or (
                                "eos_token" if req.finish_reason == "stop"
                                else "length"
                            ),
                            "generated_tokens": n_gen,
                        }
                    return self._json(200, body)

                q: queue.SimpleQueue = queue.SimpleQueue()
                req = outer.engine.submit(ids, maxnt, stream=q, **kw)
                if self._rejected(req):  # 400 beats a dead SSE stream
                    return self._req_error(req)
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.end_headers()

                def emit(tok, text, generated_text):
                    evt = {
                        "token": {"id": tok, "text": text, "special": False},
                        "generated_text": generated_text,
                    }
                    self.wfile.write(f"data: {json.dumps(evt)}\n\n".encode())
                    self.wfile.flush()

                # emit one event BEHIND so generated_text can ride the
                # last token event (the TGI schema has no token-less
                # final event)
                pieces: list[str] = []
                pending = None  # (tok, piece)
                stopped = False
                for tok in outer._stream_iter(q, req=req):
                    piece = outer._decode_tok([tok])
                    if pending is not None:
                        emit(*pending, None)
                    pieces.append(piece)
                    full, hit = cut("".join(pieces))
                    if hit:
                        stopped = True
                        emit(tok, piece, full)
                        outer.engine.cancel(req)  # free the slot: the
                        # client got its final event
                        break
                    pending = (tok, piece)
                if not stopped:
                    if req.error:
                        # match the plain stream path: clients must see
                        # the failure, not a fake successful final event
                        err = json.dumps({"error": req.error})
                        self.wfile.write(f"data: {err}\n\n".encode())
                    elif pending is not None:
                        emit(*pending, "".join(pieces))
                return None

            def _embeddings(self, payload):
                """OpenAI embeddings schema over the bert encoder
                (models/bert.py embed_texts — the same entry point the
                LangChain integration wraps)."""
                if outer.embedder is None:
                    return self._json(
                        400, {"error": "no embedding model loaded (pass "
                              "embedder=(config, params, tokenizer) to "
                              "ApiServer)"}
                    )
                texts = payload.get("input")
                if isinstance(texts, str):
                    texts = [texts]
                if (not isinstance(texts, list) or not texts
                        or not all(isinstance(t, str) for t in texts)):
                    return self._json(
                        400,
                        {"error": "input must be a string or list of strings"},
                    )
                from bigdl_tpu.models import bert as BERT

                bcfg, bparams, btok = outer.embedder
                with outer._embed_lock:
                    emb, n_tok = BERT.embed_texts(
                        bcfg, bparams, btok, texts, return_usage=True
                    )
                return self._json(200, {
                    "object": "list",
                    "data": [
                        {"object": "embedding", "index": i,
                         "embedding": e.tolist()}
                        for i, e in enumerate(emb)
                    ],
                    "model": payload.get("model", "bigdl-tpu-embed"),
                    "usage": {"prompt_tokens": n_tok,
                              "total_tokens": n_tok},
                })

            @staticmethod
            def _rejected(req):
                return req.done and req.finish_reason in (
                    "invalid", "shed"
                )

            def _timeout_504(self, req, error="generation timed out"):
                """504 with the partial output delivered (docs/serving.md):
                whether the kill came from the server's wait budget or
                the engine's own deadline, a buffered transport must not
                drop tokens a streaming client would already have
                received."""
                body = {"error": error}
                # one snapshot: the engine thread may still be appending
                # until the cancel reaps, and tokens/text must agree
                toks = list(req.out_tokens)
                if toks:
                    body["tokens"] = toks
                    body["text"] = outer._decode_tok(toks)
                return self._json(504, body)

            def _req_error(self, req):
                """One mapping for every endpoint: submit-time rejection
                ("invalid", a client mistake) is 400; overload shedding
                is 429 (queue full) / 503 (queue deadline) with a
                Retry-After derived from current throughput; a blown
                deadline is 504; anything else is a server-side 500."""
                reason = req.finish_reason
                if reason == "invalid":
                    return self._json(400, {"error": req.error})
                if reason == "shed":
                    code = 429 if req.shed_kind == "queue_full" else 503
                    return self._json(
                        code, {"error": req.error},
                        headers={"Retry-After": outer._retry_after()},
                    )
                if reason == "timeout":
                    return self._timeout_504(req, req.error)
                return self._json(500, {"error": req.error})

            def _transcribe(self, raw: bytes):
                if outer.whisper is None:
                    return self._json(
                        400, {"error": "no whisper model loaded "
                              "(pass whisper=(config, params) to ApiServer)"}
                    )
                import numpy as np

                from bigdl_tpu import audio as A
                from bigdl_tpu.models import whisper as W

                ctype = self.headers.get("Content-Type", "")
                if ctype.startswith("application/json"):
                    payload = json.loads(raw or b"{}")
                    wave = np.asarray(payload.get("audio", []), np.float32)
                    if wave.size == 0:
                        return self._json(400, {"error": "empty audio"})
                else:  # raw WAV body
                    wave = A.read_wav(raw)
                if wave.size == 0:
                    return self._json(400, {"error": "empty audio"})
                wcfg, wparams = outer.whisper
                try:
                    requested = int(self.headers.get("X-Max-New-Tokens", 128))
                except ValueError as e:
                    return self._json(400, {"error": f"bad X-Max-New-Tokens: {e}"})
                # clamp + bucket to multiples of 32: max_new_tokens is a
                # compile-time constant (whisper._generate_jit) — raw
                # client values would compile a fresh program each. The
                # response is still sliced back to the requested count.
                cap = max(1, wcfg.max_target_positions - 8)
                requested = min(max(requested, 1), cap)
                max_new = min(-(-requested // 32) * 32, cap)

                with outer._whisper_lock:
                    # 30-second windows over the full clip (the shared
                    # pipeline in whisper.transcribe_waveform — also what
                    # the WER harness scores); response honors the
                    # requested token cap across chunks
                    ids = W.transcribe_waveform(
                        wcfg, wparams, wave, max_new_tokens=max_new
                    )[:requested]
                if outer.whisper_tokenizer is not None:
                    text = outer.whisper_tokenizer.decode(
                        ids, skip_special_tokens=True
                    )
                    return self._json(200, {"text": text})
                return self._json(200, {"tokens": ids})

            # ---- endpoint bodies ----
            def _generate(self, payload, stream: bool):
                ids = outer._encode(payload.get("prompt", payload.get("inputs", "")))
                maxnt = int(payload.get("max_new_tokens", payload.get("max_tokens", 64)))
                if stream:
                    q: queue.SimpleQueue = queue.SimpleQueue()
                    req = outer.engine.submit(ids, maxnt, stream=q,
                                              **_sampling_kwargs(payload))
                    if self._rejected(req):
                        return self._req_error(req)
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.end_headers()
                    for tok in outer._stream_iter(q, req=req):
                        text = outer._decode_tok([tok])
                        evt = json.dumps({"token": tok, "text": text})
                        self.wfile.write(f"data: {evt}\n\n".encode())
                        self.wfile.flush()
                    if req.error:
                        err = json.dumps({"error": req.error})
                        self.wfile.write(f"data: {err}\n\n".encode())
                    self.wfile.write(b"data: [DONE]\n\n")
                    return None
                req = outer.engine.submit(ids, maxnt,
                                          **_sampling_kwargs(payload))
                if outer._wait(req):
                    return self._timeout_504(req)
                if req.error:
                    return self._req_error(req)
                return self._json(200, {
                    "tokens": req.out_tokens,
                    "text": outer._decode_tok(req.out_tokens),
                })

            def _completions(self, payload):
                ids = outer._encode(payload.get("prompt", ""))
                maxnt = int(payload.get("max_tokens", 64))
                req = outer.engine.submit(ids, maxnt,
                                          **_sampling_kwargs(payload))
                if outer._wait(req):
                    return self._timeout_504(req)
                if req.error:
                    return self._req_error(req)
                choice = {
                    "index": 0,
                    "text": outer._decode_tok(req.out_tokens),
                    "finish_reason": req.finish_reason or "length",
                }
                if payload.get("logprobs") is not None:
                    # OpenAI completions logprobs subset: the chosen
                    # token's log-softmax under the model (pre-filtering)
                    choice["logprobs"] = {
                        "tokens": [outer._decode_tok([t])
                                   for t in req.out_tokens],
                        "token_logprobs": req.out_logprobs,
                    }
                    n_req = 0
                    try:
                        n_req = int(payload.get("logprobs") or 0)
                    except (TypeError, ValueError):
                        pass
                    if req.out_top_logprobs and n_req > 0:
                        # honor the requested count (engine serves up to
                        # its static logprobs_top_k); on decoded-string
                        # collisions keep the HIGHER logprob
                        tops = []
                        for alt in req.out_top_logprobs:
                            d = {}
                            for t, lp in list(alt.items())[:n_req]:
                                s_tok = outer._decode_tok([t])
                                if s_tok not in d or lp > d[s_tok]:
                                    d[s_tok] = lp
                            tops.append(d)
                        choice["logprobs"]["top_logprobs"] = tops
                return self._json(200, {
                    "id": f"cmpl-{uuid.uuid4().hex[:12]}",
                    "object": "text_completion",
                    "created": int(outer._clock()),
                    "model": payload.get("model", "bigdl-tpu"),
                    "choices": [choice],
                    "usage": {
                        "prompt_tokens": len(ids),
                        "completion_tokens": len(req.out_tokens),
                        "total_tokens": len(ids) + len(req.out_tokens),
                    },
                })

            def _chat(self, payload):
                messages = payload.get("messages", [])
                ids = outer._encode_chat(messages)
                maxnt = int(payload.get("max_tokens", 64))
                if payload.get("stream"):
                    q: queue.SimpleQueue = queue.SimpleQueue()
                    req = outer.engine.submit(ids, maxnt, stream=q,
                                              **_sampling_kwargs(payload))
                    if self._rejected(req):
                        return self._req_error(req)
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.end_headers()
                    cid = f"chatcmpl-{uuid.uuid4().hex[:12]}"
                    for tok in outer._stream_iter(q, req=req):
                        chunk = {
                            "id": cid, "object": "chat.completion.chunk",
                            "choices": [{
                                "index": 0,
                                "delta": {"content": outer._decode_tok([tok])},
                            }],
                        }
                        self.wfile.write(
                            f"data: {json.dumps(chunk)}\n\n".encode()
                        )
                        self.wfile.flush()
                    if req.error:
                        err = json.dumps({"error": req.error})
                        self.wfile.write(f"data: {err}\n\n".encode())
                    self.wfile.write(b"data: [DONE]\n\n")
                    return None
                req = outer.engine.submit(ids, maxnt,
                                          **_sampling_kwargs(payload))
                if outer._wait(req):
                    return self._timeout_504(req)
                if req.error:
                    return self._req_error(req)
                return self._json(200, {
                    "id": f"chatcmpl-{uuid.uuid4().hex[:12]}",
                    "object": "chat.completion",
                    "created": int(outer._clock()),
                    "model": payload.get("model", "bigdl-tpu"),
                    "choices": [{
                        "index": 0,
                        "message": {
                            "role": "assistant",
                            "content": outer._decode_tok(req.out_tokens),
                        },
                        "finish_reason": req.finish_reason or "length",
                    }],
                })

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]

    # ---- helpers ----------------------------------------------------------

    def _encode(self, prompt) -> list[int]:
        if isinstance(prompt, list):
            return [int(t) for t in prompt]
        if self.tokenizer is None:
            raise ValueError("text prompt but no tokenizer configured")
        return list(self.tokenizer(prompt)["input_ids"])

    def _encode_chat(self, messages) -> list[int]:
        if self.tokenizer is not None and hasattr(
            self.tokenizer, "apply_chat_template"
        ):
            return list(self.tokenizer.apply_chat_template(
                messages, add_generation_prompt=True
            ))
        # tokenizer-less fallback: messages may carry raw token ids
        ids: list[int] = []
        for m in messages:
            c = m.get("content")
            if isinstance(c, list):
                ids.extend(int(t) for t in c)
            else:
                ids.extend(self._encode(c))
        return ids

    def _decode_tok(self, tokens: list[int]) -> str:
        if self.tokenizer is None:
            return ""
        return self.tokenizer.decode(tokens, skip_special_tokens=True)

    def _retry_after(self) -> int:
        """Seconds a shed client should back off: queue depth over the
        engine's observed completion throughput (conservative 30 s before
        the first completion — no rate signal yet). The lifetime-average
        rate goes stale across idle stretches, so the advice is capped:
        a shed client should re-probe within minutes regardless."""
        eng = self.engine
        rate = eng.requests_completed / max(self._clock() - self._t_start,
                                            1e-6)
        if rate <= 0:
            return 30
        depth = eng._queue.qsize() + 1
        return max(1, min(int(depth / rate) + 1, 120))

    def _stream_iter(self, q, timeout: Optional[float] = None, req=None):
        """Yield tokens until the None sentinel. A stall past the timeout
        (dead engine, injected stuck step) ends the stream AND cancels
        the request in the engine — a stalled client stream must not keep
        burning a decode slot.

        The blocking q.get tick stays real time (a queue cannot sleep on
        a simulated clock), but the stall *verdict* — has `timeout`
        elapsed since the last token — is measured on the injected
        clock, so the simulated-clock benchmark drives stream deadlines
        exactly like every other deadline."""
        timeout = self.request_timeout_s if timeout is None else timeout
        tick = min(timeout, 0.05)
        last = self._clock()
        while True:
            try:
                tok = q.get(timeout=tick)
            except queue.Empty:
                if self._clock() - last < timeout:
                    continue
                if req is not None and not req.done:
                    self.engine.cancel(req)
                    # re-check AFTER the cancel, mirroring _wait: a
                    # request that finished in the race window must not
                    # be stamped stalled or counted as a timeout
                    if not req.done:
                        # the error makes every stream consumer's
                        # post-loop branch emit a failure event — without
                        # it, a timeout-truncated stream ends with the
                        # same [DONE]/final-success shape as a complete
                        # one (the engine reaps the cancel as a clean
                        # "stop" and never clears the stamp)
                        req.error = (
                            f"stream stalled > {timeout}s; "
                            "request cancelled"
                        )
                        self.engine._bump("request_timeouts")
                return
            if tok is None:
                return
            last = self._clock()
            self.metrics.count_tokens(1)
            yield tok

    def _wait(self, req, timeout: Optional[float] = None) -> bool:
        """Block until the request finishes; True = the server-side wait
        budget expired. Callers must 504 on True without re-checking
        req.done — the engine reaps the cancel concurrently, and a late
        done/'stop' must not turn a timeout into a 200 with silently
        truncated output."""
        timeout = self.request_timeout_s if timeout is None else timeout
        t0 = self._clock()
        while not req.done and self._clock() - t0 < timeout:
            time.sleep(0.005)
        if not req.done:
            # engine-cancelling timeout: before this, a timed-out
            # buffered request kept decoding into its slot forever
            self.engine.cancel(req)
            if not req.done:
                self.engine._bump("request_timeouts")
                return True
            # lost the race: the engine finished (and, for its own
            # deadline kill, already counted) the request between our
            # last poll and the cancel — bumping would double-count it;
            # fall through to normal handling of the finished request
        if not req.error:
            self.metrics.count_tokens(len(req.out_tokens))
        return False

    # ---- lifecycle ---------------------------------------------------------

    def start(self):
        self.worker.start()
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()
        return self

    def shutdown(self, graceful: bool = False,
                 drain_timeout_s: Optional[float] = None) -> bool:
        """Stop the server. graceful=True first drains: admissions shed
        with 503 + Retry-After while the engine thread finishes every
        in-flight and queued request, bounded by `drain_timeout_s`
        (default: request_timeout_s — no client is waiting longer than
        that anyway). Either way the journal is then flushed + compacted
        (engine.close), so a clean drain leaves nothing to replay and a
        kill mid-batch still only relies on replay for the unfinished
        tail. Returns True when the drain completed (vacuously for
        graceful=False)."""
        drained = True
        if graceful:
            self.engine.begin_drain()
            timeout = (self.request_timeout_s if drain_timeout_s is None
                       else drain_timeout_s)
            deadline = self._clock() + timeout
            while not self.engine.idle():
                if self._clock() > deadline:
                    drained = False
                    break
                time.sleep(0.01)
        self.worker.stop_flag.set()
        if self.worker.is_alive():
            # the engine thread must be parked before close(): the
            # journal handle closes and compaction renames the file —
            # doing either under a live writer turns the next
            # record_done into an I/O error that kills the thread
            self.worker.join(timeout=10.0)
        if not self.worker.is_alive():
            self.engine.close()
        # else: a wedged step outlived the join budget — leave the
        # journal attached (the process is exiting anyway) and let the
        # next start's replay cover the unfinished tail
        self.httpd.shutdown()
        return drained

    def install_signal_handlers(self) -> None:
        """SIGTERM -> graceful drain + exit 0 (k8s preStop/termination
        path: deploy/k8s/serve-v5e-8.yaml's grace period must exceed
        request_timeout_s for the drain to finish). Main-thread only;
        cmd_serve calls this — embedded/test servers manage their own
        lifecycle."""
        import signal

        if threading.current_thread() is not threading.main_thread():
            return

        def _handler(signum, frame):
            # restore first: a second SIGTERM mid-drain kills for real
            signal.signal(signum, signal.SIG_DFL)
            self.shutdown(graceful=True)
            raise SystemExit(0)

        signal.signal(signal.SIGTERM, _handler)
