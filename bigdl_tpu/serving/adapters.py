"""Multi-tenant LoRA adapter serving: one quantized base, hundreds of
hot-swappable adapters (ISSUE 15; the ROADMAP "Multi-tenant LoRA
serving" item — the S-LoRA scenario, precedent in the reference's
FastChat multi-worker layer, SURVEY §L7).

The base model stays quantized and shared; each request may name a LoRA
adapter and the engine applies it as an UNQUANTIZED epilogue
``y += (x @ A) @ B * (alpha/r)`` on the shared fused dequant-GEMM
output (ops/linear.lora_epilogue) — never merge-and-requantize per
tenant (arxiv 2301.12017: requantizing a merged base compounds
quantization noise per adapter and would need a full base copy per
tenant's HBM).

Three pieces live here:

* **artifact I/O** — :func:`save_adapter` / :func:`load_adapter`: a
  LoRA tree as ONE .npz with a per-tensor integrity manifest
  (utils/durability.py), committed through the atomic
  tmp+fsync+rename protocol; loads verify in ``off|fast|full`` modes
  and raise a structured :class:`AdapterError` instead of a KeyError
  deep in a decode step;
* **AdapterRegistry** — named adapters resident in host RAM under a
  byte budget, O(1) LRU on hit, refcounted (a slot decoding with an
  adapter holds one reference — the same one-hold-per-holder rule as
  ``kvpaged.PagePool``; eviction only ever touches refcount-0,
  unpinned entries), lazy reload-by-name after eviction, and a
  seedable fault point (``adapter_load_corrupt`` in
  serving/faults.POINTS) so the corrupt-artifact path is an ordinary
  CPU test;
* **rank bucketing** — :func:`rank_bucket` rounds the max rank in a
  batch up a small power-of-two ladder, bounding the number of
  compiled decode/prefill variants: zero-padding A's rank rows and
  B's rank columns contributes exactly 0 to the epilogue, so one
  program serves every adapter at or below the bucket.

docs/serving.md §7 documents the full model.
"""

from __future__ import annotations

import collections
import json
import os
import time
import zipfile
from typing import Callable, Optional

import numpy as np

from bigdl_tpu.serving.faults import NULL_INJECTOR
from bigdl_tpu.utils import durability
from bigdl_tpu.utils.durability import IntegrityError

FORMAT_VERSION = 1

#: registry default: adapters above this rank are refused at load (the
#: bucketed decode program's cost grows with the bucket, and a single
#: huge-rank tenant would inflate every batch it rides in)
DEFAULT_MAX_RANK = 64


def rank_bucket(rank: int) -> int:
    """The compile-variant ladder: smallest power of two >= rank, with
    a floor of 4 (ranks 1-4 share one program)."""
    b = 4
    while b < rank:
        b *= 2
    return b


def lora_nbytes(lora: dict) -> int:
    """Host-RAM footprint of a LoRA tree's weight leaves — THE size the
    registry budgets, evicts on, and reports; `bigdl-tpu adapters
    inspect` and the sim's budget sizing use the same definition so an
    operator-observed nbytes always matches the accounting."""
    return sum(
        int(np.asarray(pair[leaf]).nbytes)
        for pair in lora["layers"].values() for leaf in ("a", "b")
    )


class AdapterError(ValueError):
    """Structured adapter failure. `kind` is machine-readable:

    - ``missing``: no artifact for the name (not resident, no path)
    - ``corrupt``: integrity verification failed (or injected via the
      ``adapter_load_corrupt`` fault point)
    - ``rank_mismatch``: rank/shape disagrees with the serving model
      (wrong base, a/b pair mismatch, or rank over the registry cap)
    - ``busy``: unload refused while requests hold references
    - ``budget``: the host-RAM budget cannot fit the adapter even
      after evicting every evictable entry
    - ``page_in_stall``: the device page-in of the adapter's weights
      stalled (injected via the ``adapter_page_in_stall`` fault point)
      — the request naming it finishes "error", never fail_all

    Subclasses ValueError so generic input-validation guards keep
    working; the HTTP layer maps kinds to status codes."""

    def __init__(self, name: str, kind: str, detail: str = ""):
        self.name = name
        self.kind = kind
        self.detail = detail
        super().__init__(
            f"adapter {name!r}: {kind}" + (f" — {detail}" if detail else "")
        )


# ---------------------------------------------------------------------------
# artifact I/O (durability manifests, atomic commit)
# ---------------------------------------------------------------------------

def save_adapter(path: str, lora: dict, *, faults=None) -> None:
    """Write a LoRA tree ({'layers': {target: {'a', 'b'}}, 'scale'}) as
    one verifiable .npz: per-tensor crc32/sha256 digests in the meta
    member, atomic tmp+fsync+rename commit. The serving handoff from
    train/qlora.py — a trained adapter becomes a durable artifact the
    registry can load, verify, and evict (docs/training.md)."""
    from bigdl_tpu.train.checkpoint import _encode

    arrays: dict = {}
    dtypes: dict = {}
    rank = None
    for t in sorted(lora["layers"]):
        pair = lora["layers"][t]
        a, b = np.asarray(pair["a"]), np.asarray(pair["b"])
        if a.ndim != 3 or b.ndim != 3 or a.shape[1] != b.shape[2]:
            raise AdapterError(
                os.path.basename(path), "rank_mismatch",
                f"target {t}: a {a.shape} / b {b.shape} are not "
                "[L, r, in] / [L, out, r] with one shared rank",
            )
        if rank is None:
            rank = a.shape[1]
        elif a.shape[1] != rank:
            raise AdapterError(
                os.path.basename(path), "rank_mismatch",
                f"target {t} rank {a.shape[1]} != {rank} (one rank per "
                "adapter)",
            )
        for leaf, arr in (("a", pair["a"]), ("b", pair["b"])):
            enc, dt = _encode(arr)
            arrays[f"layers/{t}/{leaf}"] = enc
            dtypes[f"layers/{t}/{leaf}"] = dt
    scale = float(np.asarray(lora["scale"], np.float32))

    def write(f) -> None:
        with zipfile.ZipFile(f, "w", zipfile.ZIP_STORED) as zf:
            tensors = {}
            for k in sorted(arrays):
                tensors[k] = durability.add_npz_member(zf, k, arrays[k])
            meta = {
                "format_version": FORMAT_VERSION,
                "rank": int(rank or 0),
                "scale": scale,
                "targets": sorted(lora["layers"]),
                "dtypes": dtypes,
                "integrity": durability.integrity_section(tensors),
            }
            durability.add_npz_member(zf, "meta",
                                      np.asarray(json.dumps(meta)))

    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    durability.atomic_write(path, write, faults=faults)


def load_adapter(path: str, verify: str = "fast") -> tuple[dict, dict]:
    """Read + verify one adapter artifact -> (lora tree with host
    numpy/bit-view leaves decoded to their logical dtypes, meta dict).
    verify: off|fast|full (utils/durability.py semantics). Raises
    FileNotFoundError for an absent file and IntegrityError for a
    damaged one — the registry wraps both into AdapterError."""
    from bigdl_tpu.train.checkpoint import _decode

    durability.check_verify_mode(verify)
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    try:
        npz = np.load(path, allow_pickle=False)
        meta = json.loads(str(npz["meta"]))
    except Exception as e:
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, detail=f"unreadable adapter: {type(e).__name__}: {e}",
        ) from e
    if meta.get("format_version") != FORMAT_VERSION:
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(
            path, detail=f"unsupported adapter format_version "
                         f"{meta.get('format_version')!r} (rotted meta?)",
        )
    targets = meta.get("targets") or []
    dtypes = meta.get("dtypes") or {}
    expected = [f"layers/{t}/{leaf}" for t in targets for leaf in ("a", "b")]
    integrity = (meta.get("integrity") or {}).get("tensors")
    arrays, corrupted, missing, extra = durability.verify_npz_members(
        path, integrity, verify, expected, ignore={"meta"},
    )
    if verify == "full":
        for k in expected:
            if k not in arrays:
                continue
            detail = durability.scan_non_finite(arrays[k], dtypes.get(k, ""))
            if detail is not None:
                corrupted[k] = f"non_finite: {detail}"
                arrays.pop(k)
    if corrupted or missing or extra:
        durability.VERIFY_FAILURES.inc()
        raise IntegrityError(path, corrupted=corrupted, missing=missing,
                             extra=extra)
    layers = {
        t: {leaf: _decode(arrays[f"layers/{t}/{leaf}"],
                          dtypes.get(f"layers/{t}/{leaf}", "float32"))
            for leaf in ("a", "b")}
        for t in targets
    }
    return {"layers": layers, "scale": float(meta.get("scale", 1.0))}, meta


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------

class AdapterEntry:
    """One resident adapter: host-RAM weights + the cached rank-padded
    device trees the engine's prefill path feeds to the model. The
    registry owns `refcount`; holders (a slot decoding with this
    adapter, a parked preempted request) each carry exactly one."""

    __slots__ = ("name", "path", "layers", "scale", "rank", "alpha",
                 "targets", "nbytes", "pinned", "refcount", "_trees")

    def __init__(self, name: str, path: Optional[str], lora: dict,
                 meta: dict, pinned: bool = False):
        self.name = name
        self.path = path
        self.layers = lora["layers"]
        self.scale = float(lora["scale"])
        self.rank = int(meta.get("rank", 0))
        self.alpha = self.scale * max(self.rank, 1)
        self.targets = tuple(sorted(self.layers))
        self.nbytes = lora_nbytes(lora)
        self.pinned = pinned
        self.refcount = 0
        self._trees: dict = {}  # rank bucket -> device tree

    def tree(self, bucket: Optional[int] = None) -> dict:
        """The single-request LoRA tree at `bucket` rank (default: this
        adapter's own bucket), A zero-padded on rank rows and B on rank
        columns — exact zeros contribute nothing to the epilogue, so
        every adapter at or below the bucket shares one compiled
        prefill/decode variant."""
        import jax.numpy as jnp

        rb = rank_bucket(self.rank) if bucket is None else bucket
        if rb in self._trees:
            return self._trees[rb]
        layers = {}
        for t, pair in self.layers.items():
            a = jnp.asarray(pair["a"])
            b = jnp.asarray(pair["b"])
            if rb > self.rank:
                a = jnp.pad(a, ((0, 0), (0, rb - self.rank), (0, 0)))
                b = jnp.pad(b, ((0, 0), (0, 0), (0, rb - self.rank)))
            layers[t] = {"a": a, "b": b}
        tree = {"layers": layers,
                "scale": jnp.asarray(self.scale, jnp.float32)}
        self._trees[rb] = tree
        return tree

    def describe(self) -> dict:
        return {
            "name": self.name, "rank": self.rank, "alpha": self.alpha,
            "targets": list(self.targets), "nbytes": self.nbytes,
            "pinned": self.pinned, "refcount": self.refcount,
        }


class AdapterRegistry:
    """Named LoRA adapters resident in host RAM under `budget_bytes`.

    Thread-safe: HTTP handler threads load/unload/pin while the engine
    thread acquires/releases per request. LRU is an OrderedDict
    (`move_to_end` on every hit, O(1) — serving/radix.py's discipline);
    eviction scans LRU-first for an entry no request references and no
    operator pinned. An evicted name is NOT forgotten: its path stays
    registered, so the next request naming it triggers a (counted)
    reload — the churn the sim's Zipf trace prices.

    `verify` (default "fast") is the load-time integrity mode; the
    ``adapter_load_corrupt`` fault point (serving/faults.py) makes the
    corrupt path deterministic in tests."""

    def __init__(self, dir: Optional[str] = None,
                 budget_bytes: Optional[int] = None,
                 verify: str = "fast",
                 max_rank: int = DEFAULT_MAX_RANK,
                 faults=None, tracer=None,
                 clock: Callable[[], float] = time.time):
        import threading

        self.dir = dir
        self.budget_bytes = budget_bytes
        self.verify = durability.check_verify_mode(verify)
        self.max_rank = max_rank
        self._faults = faults if faults is not None else NULL_INJECTOR
        self.tracer = tracer
        self._clock = clock
        self._lock = threading.RLock()
        # name -> entry, least-recently-used first
        self._entries: "collections.OrderedDict[str, AdapterEntry]" = \
            collections.OrderedDict()
        self._paths: dict[str, str] = {}  # every name ever loaded
        # observability (serving/metrics.py renders these)
        self.loads = 0          # artifact reads (incl. post-evict reloads)
        self.hits = 0           # get() served from residency
        self.evictions = 0      # budget-pressure drops
        self.load_failures = 0  # missing/corrupt/mismatched artifacts

    def bind(self, tracer=None, clock=None,
             faults=None) -> "AdapterRegistry":
        """Late wiring for servers that construct their tracer/clock/
        injector after the registry (ApiServer does). An injector the
        registry was EXPLICITLY constructed with is never clobbered —
        the server's only fills the inert default, so arming
        adapter_load_corrupt on the server-level injector reaches the
        registry too."""
        if tracer is not None:
            self.tracer = tracer
        if clock is not None:
            self._clock = clock
        if faults is not None and self._faults is NULL_INJECTOR:
            self._faults = faults
        return self

    # -- internals (call with the lock held) --------------------------------

    def _instant(self, event: str, **args) -> None:
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.instant(event, ts=self._clock(), tid=0, cat="adapter",
                       **args)

    def _resolve_path(self, name: str, path: Optional[str]) -> str:
        if path is not None:
            return path
        if name in self._paths:
            return self._paths[name]
        if self.dir is not None:
            cand = os.path.join(self.dir, f"{name}.npz")
            if os.path.exists(cand):
                return cand
            cand = os.path.join(self.dir, name)
            if os.path.exists(cand):
                return cand
        raise AdapterError(
            name, "missing",
            "not resident and no artifact path known"
            + (f" under {self.dir}" if self.dir else
               " (no adapter dir configured)"),
        )

    def _resident_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def _evict_for(self, name: str, nbytes: int) -> None:
        """Free budget room for `nbytes`, LRU-first, refcount-0 and
        unpinned entries only — an adapter a slot is decoding with (or
        a parked request will resume with) is never dropped."""
        if self.budget_bytes is None:
            return
        while self._resident_bytes() + nbytes > self.budget_bytes:
            victim = None
            for e in self._entries.values():  # LRU -> MRU
                if e.refcount == 0 and not e.pinned:
                    victim = e
                    break
            if victim is None:
                raise AdapterError(
                    name, "budget",
                    f"{nbytes} bytes over budget "
                    f"{self.budget_bytes} and every resident adapter "
                    "is referenced or pinned",
                )
            del self._entries[victim.name]
            self.evictions += 1
            self._instant("adapter_evict", name=victim.name,
                          nbytes=victim.nbytes)

    def _load_locked(self, name: str, path: Optional[str],
                     pin: bool) -> AdapterEntry:
        resolved = self._resolve_path(name, path)
        t0 = self._clock()
        if self._faults.fire("adapter_load_corrupt") is not None:
            self.load_failures += 1
            raise AdapterError(
                name, "corrupt",
                f"injected corrupt artifact ({resolved}; fault point "
                "adapter_load_corrupt)",
            )
        try:
            lora, meta = load_adapter(resolved, verify=self.verify)
        except FileNotFoundError as e:
            self.load_failures += 1
            raise AdapterError(name, "missing", str(e)) from e
        except IntegrityError as e:
            self.load_failures += 1
            raise AdapterError(name, "corrupt", str(e)) from e
        entry = AdapterEntry(name, resolved, lora, meta, pinned=pin)
        if entry.rank < 1 or entry.rank > self.max_rank:
            self.load_failures += 1
            raise AdapterError(
                name, "rank_mismatch",
                f"rank {entry.rank} outside [1, {self.max_rank}] "
                "(registry max_rank)",
            )
        self._evict_for(name, entry.nbytes)
        self._entries[name] = entry  # most-recently-used
        self._paths[name] = resolved
        self.loads += 1
        self._instant("adapter_load", name=name, rank=entry.rank,
                      nbytes=entry.nbytes,
                      seconds=round(self._clock() - t0, 6))
        return entry

    # -- operator surface ----------------------------------------------------

    def load(self, name: str, path: Optional[str] = None,
             pin: bool = False) -> dict:
        """Load (or reload) an adapter into residency; returns its
        description. POST /adapters/load lands here."""
        with self._lock:
            old = self._entries.get(name)
            if old is not None and old.refcount > 0:
                # a reload under live references would swap weights
                # mid-decode for those requests; keep it explicit
                raise AdapterError(
                    name, "busy",
                    f"{old.refcount} in-flight request(s) hold it; "
                    "unload requires refcount 0",
                )
            if old is not None:
                # drop the old entry for the duration of the load so
                # _evict_for doesn't double-count its bytes — but a
                # FAILED reload (typo'd path, corrupt artifact) must
                # not cost the healthy resident entry or its pin
                del self._entries[name]
            try:
                entry = self._load_locked(name, path, pin)
            except Exception:
                if old is not None:
                    self._entries[name] = old  # restore, MRU position
                raise
            return entry.describe()

    def unload(self, name: str) -> dict:
        """Drop an adapter from residency (its path stays known, so a
        later request can lazily reload it). Refused while referenced."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise AdapterError(name, "missing", "not resident")
            if entry.refcount > 0:
                raise AdapterError(
                    name, "busy",
                    f"{entry.refcount} in-flight request(s) hold it",
                )
            del self._entries[name]
            self._instant("adapter_unload", name=name)
            return entry.describe()

    def pin(self, name: str, pinned: bool = True) -> dict:
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise AdapterError(name, "missing", "not resident")
            entry.pinned = pinned
            return entry.describe()

    # -- engine surface ------------------------------------------------------

    def get(self, name: str) -> AdapterEntry:
        """The entry for `name`, LRU-refreshed; lazily reloads an
        evicted (or never-loaded, when `dir` is set) adapter."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is not None:
                self._entries.move_to_end(name)
                self.hits += 1
                return entry
            return self._load_locked(name, None, pin=False)

    def acquire(self, name: str) -> AdapterEntry:
        """get() + one reference: the caller (an admitted request) now
        holds the adapter resident until release()."""
        with self._lock:
            entry = self.get(name)
            entry.refcount += 1
            return entry

    def release(self, entry: AdapterEntry) -> None:
        with self._lock:
            entry.refcount -= 1
            if entry.refcount < 0:  # double-release corrupts the budget
                # accounting silently later; fail at the faulting site
                # (kvpaged.PagePool.decref's discipline)
                raise AssertionError(
                    f"adapter {entry.name!r} refcount went negative"
                )

    def reject(self, entry: AdapterEntry, held: bool = True) -> None:
        """Release (when the caller holds a reference) + drop an entry
        the CALLER found unusable — dimension validation happens
        against the serving model, which the registry cannot see.
        Counted as a load failure (the artifact is as broken for this
        deployment as a corrupt one) and evicted from residency so it
        neither squats on budget nor serves `hits` to every retry of
        the doomed tenant."""
        with self._lock:
            if held:
                self.release(entry)
            self.load_failures += 1
            if (self._entries.get(entry.name) is entry
                    and entry.refcount == 0):
                del self._entries[entry.name]
                self._instant("adapter_evict", name=entry.name,
                              nbytes=entry.nbytes, rejected=True)

    def peek(self, name: str) -> Optional[AdapterEntry]:
        """The resident entry for `name`, with NO side effects — no LRU
        refresh, no hit count, no lazy reload (validation paths must
        not skew the churn counters request traffic is measured by)."""
        with self._lock:
            return self._entries.get(name)

    # -- observability -------------------------------------------------------

    def resident(self) -> list:
        with self._lock:
            return [e.describe() for e in self._entries.values()]

    def stats(self) -> dict:
        with self._lock:
            return {
                "loads": self.loads,
                "hits": self.hits,
                "evictions": self.evictions,
                "load_failures": self.load_failures,
                "resident": len(self._entries),
                "resident_bytes": self._resident_bytes(),
                "budget_bytes": self.budget_bytes,
            }


# ---------------------------------------------------------------------------
# unified HBM paging: adapter weights in the KV page pool
# ---------------------------------------------------------------------------

class _PagedAdapter:
    """One device-resident adapter: its physical pages (each carrying
    the pager's ONE PagePool reference), the leaf shapes needed to
    reconstruct (A, B) from the flat page frame, and the rids holding
    it resident (one hold per in-flight request — the same
    one-hold-per-holder rule as the registry and the PagePool)."""

    __slots__ = ("name", "pages", "shapes", "n_elems", "holders")

    def __init__(self, name, pages, shapes, n_elems):
        self.name = name
        self.pages = pages
        self.shapes = shapes
        self.n_elems = n_elems
        self.holders: set = set()


class AdapterPager:
    """Device residency for resident adapters' (A, B) weight leaves,
    allocated from the serving engine's KV :class:`kvpaged.PagePool` —
    ONE HBM budget for KV and adapters (the S-LoRA unified paging
    model, docs/serving.md §7). Engine-thread only (no lock): page-in
    happens at admission, page-out under the engine's own allocation
    escalation.

    Lifecycle:

    * **page-in** (:meth:`ensure`): flatten the entry's host leaves at
      its OWN rank (bucket padding happens at gather time, device
      side), allocate pages through the engine's radix-escalated
      allocator, scatter into the :class:`kvpaged.AdapterPageStore`.
      A dry pool (even after radix eviction) is NOT fatal: the caller
      falls back to host-sourced gathers for that adapter — page-in
      never preempts KV.
    * **page-out** (:meth:`evict_one`): LRU-first holder-free adapter
      drops its device pages (decref -> free list). The host copy in
      the AdapterRegistry survives, so "page-out to host" is a free
      drop, and the next request naming the tenant pages back in.
    * eviction order under page pressure (engine._alloc_page): radix
      leaf -> refcount-0 adapter page-out -> preemption.

    ``scale`` stays host-side registry metadata (f32) — only the bf16
    A/B leaves are paged, so paging is parity-exact with the host path
    (the epilogue computes in bf16 either way)."""

    def __init__(self, store, pool, alloc: Callable[[], Optional[int]],
                 faults=None):
        self.store = store
        self._pool = pool
        self._alloc = alloc
        self._faults = faults if faults is not None else NULL_INJECTOR
        # name -> _PagedAdapter, least-recently-used first
        self._res: "collections.OrderedDict[str, _PagedAdapter]" = \
            collections.OrderedDict()
        # observability (serving/metrics.py + the sim report)
        self.page_ins = 0   # pages written device-ward
        self.page_outs = 0  # pages dropped back to the free list

    @property
    def pages_resident(self) -> int:
        return sum(len(r.pages) for r in self._res.values())

    def held_pages(self):
        for rec in self._res.values():
            yield from rec.pages

    def ensure(self, entry: AdapterEntry, rid: int) -> bool:
        """Make `entry` device-resident and add `rid`'s hold. False =
        the pool stayed dry after eviction (caller uses host fallback).
        Raises AdapterError(kind="page_in_stall") when the fault point
        fires — the caller quarantines ONE request, never the batch."""
        rec = self._res.get(entry.name)
        if rec is not None:
            self._res.move_to_end(entry.name)
            rec.holders.add(rid)
            return True
        if self._faults.fire("adapter_page_in_stall") is not None:
            raise AdapterError(
                entry.name, "page_in_stall",
                "injected device page-in stall (fault point "
                "adapter_page_in_stall)",
            )
        flats, shapes = [], []
        for t in entry.targets:
            for leaf in ("a", "b"):
                arr = np.asarray(entry.layers[t][leaf], np.float32)
                shapes.append((t, leaf, arr.shape))
                flats.append(arr.ravel())
        flat = (np.concatenate(flats) if flats
                else np.zeros((0,), np.float32))
        pages: list = []
        for _ in range(self.store.n_for(flat.size)):
            pg = self._alloc()
            if pg is None:
                # dry even after radix + adapter eviction: give the
                # pages back and serve this tenant from host RAM —
                # admission semantics are unchanged, only the gather
                # source differs
                for p in pages:
                    self._pool.decref(p)
                return False
            pages.append(pg)
        try:
            self.store.write(pages, flat)
        except Exception:
            # the device scatter is a fault point (host OOM, bad
            # artifact dtype, injected stall): its raise must not
            # strand the freshly-allocated page refs — nothing holds
            # them yet, so give them straight back and let the caller
            # quarantine the one request (graftlint PAGE002)
            for p in pages:
                self._pool.decref(p)
            raise
        self.page_ins += len(pages)
        rec = _PagedAdapter(entry.name, pages, shapes, int(flat.size))
        rec.holders.add(rid)
        self._res[entry.name] = rec  # most-recently-used
        return True

    def leaves(self, name: str) -> Optional[dict]:
        """Device-side {target: {'a', 'b'}} bf16 leaves for a RESIDENT
        adapter (LRU-refreshed), or None — the engine's _gather_blora
        reads pages instead of re-transferring host weights."""
        rec = self._res.get(name)
        if rec is None:
            return None
        self._res.move_to_end(name)
        flat = self.store.read(rec.pages, rec.n_elems)
        out: dict = {}
        off = 0
        for t, leaf, shape in rec.shapes:
            sz = 1
            for d in shape:
                sz *= int(d)
            out.setdefault(t, {})[leaf] = flat[off:off + sz].reshape(shape)
            off += sz
        return out

    def drop_holder(self, rid: int) -> None:
        """Release `rid`'s holds (terminal finish). The adapter STAYS
        resident — holder-free residency is what the LRU evicts under
        pressure, not what release drops (warm reuse is the point)."""
        for rec in self._res.values():
            rec.holders.discard(rid)

    def evict_one(self) -> bool:
        """Page out the LRU holder-free adapter; False when every
        resident adapter is held (the allocator escalates to
        preemption)."""
        victim = None
        for rec in self._res.values():  # LRU -> MRU
            if not rec.holders:
                victim = rec
                break
        if victim is None:
            return False
        for pg in victim.pages:
            self._pool.decref(pg)
        self.page_outs += len(victim.pages)
        del self._res[victim.name]
        return True

    def reset(self, pool) -> None:
        """Post-crash rebuild (engine._reset_state): the old PagePool
        died with the cache, so residency is simply forgotten — no
        decrefs against a pool that no longer exists. Counters survive
        (engine totals, not cache state)."""
        self._pool = pool
        self._res.clear()
