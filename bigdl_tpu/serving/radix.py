"""Radix-tree prefix cache over the paged KV pool (ISSUE 14; the
throughput half of the ROADMAP "millions of users" scheduler).

Replaces the flat full-page-hash cache that lived in
`serving/engine.py` (a `dict[tuple(prefix) -> page]` + one-level
`_prefix_children` sets + O(n) `_prefix_lru` lists) with a true tree
over the physical pages of `kvpaged.PagedKVCache`:

- **one node per physical page**: a node covers exactly one page worth
  of prompt tokens (`tokens`, length == page_size) and owns one
  reference on its physical page in the shared `kvpaged.PagePool` —
  a page is freed exactly when no slot's block table and no cached
  node holds it, with no "cached but refcount 0" reconciliation;
- **O(prompt) incremental keys**: descending the tree hashes one
  page-sized token chunk per level instead of re-hashing the whole
  growing prefix per level (the flat cache's `tuple(prompt[:k*page])`
  keys cost O(P²/page) per admission);
- **longest-prefix match at any split point**: the full-page descent
  finds the deepest cached run, then `match_partial` scans only that
  node's direct children for the best mid-page agreement — the engine
  copies those KV slots via its existing `_copy_page` path instead of
  re-prefilling them;
- **O(1) LRU** (`OrderedDict.move_to_end` on hit — the flat cache
  paid an O(n) `list.remove` per hit and per eviction) with
  **leaf-first eviction**: only nodes with no children are evicted, so
  a cached chain is consumed tail-first and an interior page is never
  stranded unreachable; eviction unlinks the node from its parent, so
  divergence scans can never walk dead entries (the flat cache's
  `_prefix_children` accumulated keys of evicted pages forever).

Composition (docs/serving.md §6): eviction only ever touches pages
whose sole reference is the cache's own, so it can never steal a page
from a live slot or from a host-RAM-parked request's future swap-in —
preemption (PR 6) and journal replay (PR 7) see cached pages exactly
like any other allocation. The engine escalates allocation pressure as
free list -> radix eviction -> preemption.

This module is pure host-side bookkeeping: no jax, no clock reads.
"""

from __future__ import annotations

import collections
from typing import Iterator, Optional


class RadixNode:
    """One cached physical page: `tokens` is the page-content chunk it
    covers (its edge label from `parent`), `page` the physical page id
    holding that chunk's KV."""

    __slots__ = ("tokens", "page", "parent", "children")

    def __init__(self, tokens: tuple, page: int,
                 parent: Optional["RadixNode"]):
        self.tokens = tokens
        self.page = page
        self.parent = parent
        self.children: dict = {}  # tokens-tuple -> RadixNode


class RadixPrefixCache:
    """The tree + its LRU. The engine owns the hit/eviction counters
    (they must survive `_reset_state`, which rebuilds this object);
    the cache owns structure and page references only."""

    def __init__(self, page_size: int, pool):
        self.page_size = page_size
        self.pool = pool  # kvpaged.PagePool: one hold per cached node
        self.root = RadixNode((), -1, None)
        # adapter namespaces (docs/serving.md §7): KV pages prefilled
        # under a LoRA adapter carry that adapter's shifted K/V from
        # the first adapted layer up — sharing them with another tenant
        # (or the base) would silently leak one fine-tune's activations
        # into another's generation. Each namespace gets its own root,
        # so cross-tenant pages are unreachable BY CONSTRUCTION; all
        # namespaces share one LRU and one eviction policy.
        self._ns_roots: dict = {}  # adapter name -> RadixNode
        # node -> None, least-recently-used first. Hits move_to_end
        # (O(1)); eviction scans from the front for the first leaf
        # whose page only the cache holds.
        self._lru: "collections.OrderedDict[RadixNode, None]" = \
            collections.OrderedDict()

    # -- queries -------------------------------------------------------------

    @property
    def n_nodes(self) -> int:
        return len(self._lru)

    def nodes(self) -> Iterator[RadixNode]:
        return iter(self._lru)

    def root_for(self, ns=None) -> RadixNode:
        """The descent root for `ns` (an adapter name; None = the
        shared base namespace). Created on first use — a namespace with
        no cached pages costs one dict entry."""
        if ns is None:
            return self.root
        root = self._ns_roots.get(ns)
        if root is None:
            root = self._ns_roots[ns] = RadixNode((), -1, None)
        return root

    def match(self, prompt: list, ns=None) -> list:
        """The longest cached run of full pages prefixing `prompt`,
        leaving at least one tail token to prefill (its logits seed
        generation). Returns the node path root-first; every matched
        node is LRU-refreshed. O(len(prompt)) total hashing. `ns`
        selects the adapter namespace (see `root_for`)."""
        page = self.page_size
        node, path = self.root_for(ns), []
        while (len(path) + 1) * page <= len(prompt) - 1:
            lo = len(path) * page
            child = node.children.get(tuple(prompt[lo:lo + page]))
            if child is None:
                break
            path.append(child)
            node = child
        for nd in path:
            self._lru.move_to_end(nd)
        return path

    def match_len(self, prompt: list, ns=None) -> int:
        """Read-only probe: how many prompt tokens the cached full-page
        run would cover (same descent bound as `match`, but NO LRU
        refresh — the admission-ordering sort key must not promote
        entries for requests that merely got scored). Namespaced like
        `match`: a tenant's score counts only its own cached pages —
        and, staying read-only, never materializes a root for a
        namespace nothing has cached under yet."""
        page = self.page_size
        if ns is None:
            node = self.root
        else:
            node = self._ns_roots.get(ns)
            if node is None:
                return 0
        depth = 0
        while (depth + 1) * page <= len(prompt) - 1:
            lo = depth * page
            child = node.children.get(tuple(prompt[lo:lo + page]))
            if child is None:
                break
            depth += 1
            node = child
        return depth * page

    def match_partial(self, node: RadixNode, tail: list):
        """Best mid-page extension under `node`: the child page whose
        tokens agree with `tail` longest. Returns (t_agree, child);
        (0, None) when nothing agrees. The caller caps t_agree and
        decides whether the copy pays (bucket-plan quantization)."""
        best_m, best = 0, None
        for child in node.children.values():
            m = 0
            for a, b in zip(child.tokens, tail):
                if a != b:
                    break
                m += 1
            if m > best_m:
                best_m, best = m, child
        return best_m, best

    def touch(self, node: RadixNode) -> None:
        """LRU-refresh a node that just proved hot (partial-copy
        source)."""
        self._lru.move_to_end(node)

    # -- mutation ------------------------------------------------------------

    def insert(self, parent: RadixNode, tokens, page: int) -> RadixNode:
        """Register `page` as `parent`'s child covering `tokens`,
        taking the cache's own page reference. The caller guarantees
        the edge does not exist (use `parent.children.get` first —
        an existing edge keeps its canonical page)."""
        key = tuple(tokens)
        assert key not in parent.children
        node = RadixNode(key, page, parent)
        parent.children[key] = node
        self.pool.incref(page)
        self._lru[node] = None  # most-recently-used
        return node

    def evict_one(self) -> bool:
        """Drop the least-recently-used evictable node: a leaf (an
        interior node anchors a live chain — evicting it would strand
        its descendants unreachable) whose page carries no hold beyond
        the cache's own. Unlinks it from its parent (no stale child
        keys) and releases the page to the pool's free list. Returns
        False when nothing is evictable (every cached page is also in
        some slot's table, or the cache is empty)."""
        victim = None
        for node in self._lru:  # LRU -> MRU
            if not node.children and self.pool.ref[node.page] == 1:
                victim = node
                break
        if victim is None:
            return False
        del self._lru[victim]
        del victim.parent.children[victim.tokens]
        victim.parent = None
        self.pool.decref(victim.page)  # -> 0: back on the free list
        return True

    def clear(self) -> None:
        """Release every cached page (engine `_reset_state`: the pool
        is rebuilt alongside, so holds must not linger)."""
        for node in self._lru:
            self.pool.decref(node.page)
            node.parent = None
            node.children.clear()
        self._lru.clear()
        self.root = RadixNode((), -1, None)
        self._ns_roots = {}

    # -- invariants (tests + engine leak accounting) -------------------------

    def check(self) -> None:
        """Structural invariants: every reachable node is LRU-tracked
        and vice versa (a violation means dead nodes — the flat
        cache's stale-children bug class), every cached page holds at
        least the cache's reference, and edge labels are page-sized."""
        reachable = set()
        stack = [self.root, *self._ns_roots.values()]
        while stack:
            nd = stack.pop()
            for key, child in nd.children.items():
                assert key == child.tokens and child.parent is nd
                assert len(child.tokens) == self.page_size
                assert self.pool.ref[child.page] >= 1, (
                    f"cached page {child.page} has no reference"
                )
                reachable.add(child)
                stack.append(child)
        tracked = set(self._lru)
        assert reachable == tracked, (
            f"{len(tracked - reachable)} dead (unreachable) nodes, "
            f"{len(reachable - tracked)} untracked nodes"
        )
