"""FastChat model-worker protocol over the continuous-batching engine.

Role-equivalent of the reference's `BigDLLLMWorker`
(/root/reference/python/llm/src/ipex_llm/serving/fastchat/ipex_llm_worker.py:
58-468): a worker process that (1) registers itself with a FastChat
controller, (2) heartbeats its queue length so the controller can route,
and (3) serves the worker HTTP surface — `/worker_generate_stream`,
`/worker_generate`, `/worker_get_status`, `/count_token`,
`/model_details`, `/worker_get_conv_template` — so this framework drops
into an existing FastChat deployment (controller + openai_api_server)
as a drop-in worker.

Design differences from the reference, not omissions:
- stdlib-only (ThreadingHTTPServer + urllib), matching api_server.py —
  no FastAPI/uvicorn dependency for the runtime;
- generation runs through the slot-pool continuous-batching engine, so
  one worker serves `limit_worker_concurrency` requests CONCURRENTLY
  (the reference's worker serializes behind a semaphore);
- streaming frames follow the FastChat wire format: JSON chunks
  terminated by NUL (b"\\0"), each {"text", "error_code", "usage",
  "finish_reason"} with cumulative text.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib import request as urlrequest

from bigdl_tpu.serving.api_server import _EngineThread, _sampling_kwargs
from bigdl_tpu.serving.engine import InferenceEngine

HEARTBEAT_S = 45  # FastChat controller expiry default is 90s


class FastChatWorker:
    def __init__(
        self,
        model,
        tokenizer=None,
        controller_addr: Optional[str] = None,  # e.g. http://host:21001
        worker_addr: Optional[str] = None,  # how the controller reaches us
        model_names: Optional[list[str]] = None,
        host: str = "127.0.0.1",
        port: int = 21002,
        n_slots: int = 8,
        max_len: int = 2048,
        gen=None,
        paged: bool = False,
        speculative: bool = False,
        draft_k: int = 4,
        heartbeat_s: float = HEARTBEAT_S,
        truncate_prompts: bool = False,
        journal: Optional[str] = None,  # crash-recovery request journal
        # overload protection (docs/serving.md), same knobs as ApiServer
        max_queue: Optional[int] = None,
        queue_deadline_s: Optional[float] = None,
        deadline_s: Optional[float] = None,
        preemption: bool = True,
        adapters=None,  # AdapterRegistry (serving/adapters.py): worker
        # payloads gain an "adapter" field — one FastChat worker serves
        # many tenants' fine-tunes over one shared base
    ):
        self.adapters = adapters
        self.engine = InferenceEngine(
            model, n_slots=n_slots, max_len=max_len, gen=gen,
            paged=paged, speculative=speculative, draft_k=draft_k,
            truncate_prompts=truncate_prompts, journal=journal,
            max_queue=max_queue, queue_deadline_s=queue_deadline_s,
            deadline_s=deadline_s, preemption=preemption,
            adapters=adapters,
        )
        self.tokenizer = tokenizer
        self.controller_addr = controller_addr
        self.model_names = model_names or ["bigdl-tpu"]
        self.worker_id = uuid.uuid4().hex[:8]
        self.max_len = max_len
        self.call_ct = 0
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.worker = _EngineThread(self.engine)
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self.heartbeat_s = heartbeat_s
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                try:
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except json.JSONDecodeError:
                    return self._json(400, {"error": "invalid JSON"})
                route = self.path
                if route == "/worker_get_status":
                    return self._json(200, outer.status())
                if route == "/count_token":
                    try:
                        count = len(outer._encode(payload.get("prompt", "")))
                        return self._json(200, {"count": count,
                                                "error_code": 0})
                    except ValueError as e:  # text prompt, no tokenizer
                        return self._json(200, {"count": 0,
                                                "error_code": 50001,
                                                "text": str(e)})
                if route == "/model_details":
                    return self._json(200, {"context_length": outer.max_len})
                if route == "/worker_get_conv_template":
                    # a full Conversation field dict — the FastChat API
                    # server instantiates it directly, so None would
                    # crash every chat completion. sep_style 1 =
                    # ADD_COLON_SINGLE, the registry's generic default.
                    return self._json(200, {"conv": {
                        "name": outer.model_names[0],
                        "system_template": "{system_message}",
                        "system_message": "",
                        "roles": ["USER", "ASSISTANT"],
                        "messages": [],
                        "offset": 0,
                        "sep_style": 1,
                        "sep": "\n",
                        "sep2": None,
                        "stop_str": None,
                        "stop_token_ids": None,
                    }})
                if route == "/worker_generate":
                    return self._json(200, outer._generate_blocking(payload))
                if route == "/worker_generate_stream":
                    self.send_response(200)
                    self.send_header("Content-Type", "application/json")
                    self.end_headers()
                    for chunk in outer._generate_stream(payload):
                        self.wfile.write(json.dumps(chunk).encode() + b"\0")
                        self.wfile.flush()
                    return None
                return self._json(404, {"error": f"no route {route}"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self.worker_addr = worker_addr or f"http://{host}:{self.port}"

    # ---- controller protocol ---------------------------------------------

    def status(self) -> dict:
        return {
            "model_names": self.model_names,
            "speed": 1,
            "queue_length": self._inflight,
        }

    def _post_controller(self, route: str, obj: dict) -> dict:
        req = urlrequest.Request(
            self.controller_addr + route,
            data=json.dumps(obj).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urlrequest.urlopen(req, timeout=15) as resp:
            body = resp.read()
            return json.loads(body) if body else {}

    def register(self) -> None:
        """POST /register_worker — the FastChat controller handshake."""
        self._post_controller("/register_worker", {
            "worker_name": self.worker_addr,
            "check_heart_beat": True,
            "worker_status": self.status(),
        })

    def _heartbeat_loop(self) -> None:
        while not self._hb_stop.wait(self.heartbeat_s):
            try:
                resp = self._post_controller("/receive_heart_beat", {
                    "worker_name": self.worker_addr,
                    "queue_length": self._inflight,
                })
                if not resp.get("exist", True):
                    self.register()  # controller restarted: re-handshake
            except Exception:  # noqa: BLE001 — controller outage: retry
                pass

    # ---- generation -------------------------------------------------------

    def _encode(self, prompt) -> list[int]:
        if isinstance(prompt, list):
            return [int(t) for t in prompt]
        if self.tokenizer is None:
            raise ValueError("text prompt but no tokenizer configured")
        return list(self.tokenizer(prompt)["input_ids"])

    def _decode(self, tokens: list[int]) -> str:
        if self.tokenizer is None:
            return " ".join(str(t) for t in tokens)
        return self.tokenizer.decode(tokens, skip_special_tokens=True)

    def _submit(self, payload: dict):
        self.call_ct += 1
        ids = self._encode(payload.get("prompt", ""))
        maxnt = int(payload.get("max_new_tokens", 256))
        kw = _sampling_kwargs(payload)
        # the engine knows ONE eos id; the full stop_token_ids set is
        # enforced worker-side in _generate_stream (any match cuts)
        stop_ids = {int(t) for t in payload.get("stop_token_ids") or []}
        if "eos_token_id" not in kw and stop_ids:
            kw["eos_token_id"] = next(iter(stop_ids))
        q: queue.SimpleQueue = queue.SimpleQueue()
        req = self.engine.submit(ids, maxnt, stream=q, **kw)
        return ids, req, q, stop_ids

    STREAM_INTERVAL = 2  # decode/emit every N tokens (reference default)

    def _generate_stream(self, payload: dict):
        """FastChat chunk protocol: cumulative text per frame, final
        frame carries finish_reason. Frames are emitted every
        STREAM_INTERVAL tokens (decode re-runs over the full output per
        frame — per-token frames would be O(n^2) detokenization)."""
        echo = bool(payload.get("echo", False))
        stops = payload.get("stop")
        stops = ([stops] if isinstance(stops, str) else list(stops or []))
        try:
            ids, req, q, stop_ids = self._submit(payload)
        except ValueError as e:
            yield {"text": str(e), "error_code": 50001, "usage": {},
                   "finish_reason": None}
            return
        with self._inflight_lock:
            self._inflight += 1
        finished = False
        try:
            prefix = self._decode(ids) if echo else ""
            toks: list[int] = []
            cut = None
            while True:
                try:
                    tok = q.get(timeout=300.0)
                except queue.Empty:
                    yield {"text": "generation timed out",
                           "error_code": 50004, "usage": {},
                           "finish_reason": "error"}
                    return
                if tok is None:
                    break
                if tok in stop_ids:  # any stop id cuts (engine knows one)
                    cut = "stop"
                    self.engine.cancel(req)
                    break
                toks.append(tok)
                if len(toks) % self.STREAM_INTERVAL:
                    continue
                text = self._decode(toks)
                for s in stops:  # stop-string cut, FastChat semantics
                    i = text.find(s)
                    if i >= 0:
                        cut, text = "stop", text[:i]
                        break
                yield {
                    "text": prefix + text,
                    "error_code": 0,
                    "usage": {
                        "prompt_tokens": len(ids),
                        "completion_tokens": len(toks),
                        "total_tokens": len(ids) + len(toks),
                    },
                    "finish_reason": None,
                }
                if cut:
                    self.engine.cancel(req)
                    break
            final_text = self._decode(toks)
            if cut:
                for s in stops:
                    i = final_text.find(s)
                    if i >= 0:
                        final_text = final_text[:i]
                        break
            if req.error:
                # 50007 = FastChat CONTEXT_OVERFLOW: a client mistake
                # (over-long prompt rejected at submit), not a worker
                # failure — gateways must not health-flap on it.
                # 42903 = ENGINE_OVERLOADED: shed requests (queue bound
                # / queue deadline) and per-request deadline kills
                # (docs/serving.md) are retryable load pressure, not
                # worker failures either.
                code = {"invalid": 50007, "shed": 42903,
                        "timeout": 42903}.get(req.finish_reason, 50002)
                yield {"text": req.error, "error_code": code, "usage": {},
                       "finish_reason": "error"}
            else:
                yield {
                    "text": prefix + final_text,
                    "error_code": 0,
                    "usage": {
                        "prompt_tokens": len(ids),
                        "completion_tokens": len(toks),
                        "total_tokens": len(ids) + len(toks),
                    },
                    "finish_reason": cut or req.finish_reason or "length",
                }
            finished = True
        finally:
            if not finished and not req.done:
                # client disconnect (GeneratorExit via BrokenPipeError) or
                # timeout: stop burning decode steps for a gone consumer
                self.engine.cancel(req)
            with self._inflight_lock:
                self._inflight -= 1

    def _generate_blocking(self, payload: dict) -> dict:
        last = {"text": "", "error_code": 50002, "usage": {},
                "finish_reason": "error"}
        for last in self._generate_stream(payload):
            pass
        return last

    # ---- lifecycle --------------------------------------------------------

    def start(self, register: bool = True) -> None:
        self.worker.start()
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        if register and self.controller_addr:
            self.register()
            self._hb_thread = threading.Thread(
                target=self._heartbeat_loop, daemon=True
            )
            self._hb_thread.start()

    def shutdown(self) -> None:
        self._hb_stop.set()
        self.httpd.shutdown()
        self.worker.stop_flag.set()
