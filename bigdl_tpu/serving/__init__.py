"""Serving stack (reference: `serving/fastapi/` lightweight OpenAI server
+ the PPModelWorker continuous-batching scheduler,
pipeline_parallel.py:482-929 in /root/reference)."""

from bigdl_tpu.serving.engine import InferenceEngine, Request

__all__ = ["InferenceEngine", "Request"]
