"""Serving stack (reference: `serving/fastapi/` lightweight OpenAI server
+ the PPModelWorker continuous-batching scheduler,
pipeline_parallel.py:482-929 in /root/reference)."""

from bigdl_tpu.serving.engine import InferenceEngine, Request

__all__ = ["InferenceEngine", "Request", "FastChatWorker"]


def __getattr__(name):
    if name == "FastChatWorker":  # lazy: keeps engine-only imports light
        from bigdl_tpu.serving.fastchat_worker import FastChatWorker

        return FastChatWorker
    raise AttributeError(name)
