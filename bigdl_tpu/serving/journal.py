"""Crash-recovery request journal for the serving engine.

The reference's serving stack restarts through its k8s job specs but
loses every in-flight request on a worker crash (the FastAPI worker's
queue and the PPModelWorker batch state are process-local,
reference serving/fastapi/model_worker.py:28-200). TPU serving gets a
first-class restart story instead: every accepted request is appended
to a JSONL journal, completions append a tombstone, and a fresh engine
replays the unfinished tail into `engine.recovered_requests` — pairing with
deploy/'s restartPolicy so a killed pod resumes its queue instead of
dropping it.

Format: one JSON object per line.
  {"op": "submit", "rid": 7, "prompt": [...], "max_new_tokens": 64, ...}
  {"op": "done", "rid": 7}

A request is pending iff its last submit has no matching done. Replayed
requests get NEW rids (each old entry is superseded by a tombstone once
its replacement is recorded), and streaming consumers are not
resurrected — a replayed request completes as a plain buffered request
retrievable via the API server's GET /recovered.
"""

from __future__ import annotations

import json
import os
import threading
import warnings
from typing import Optional

# sampling/stop/deadline fields that survive a restart (stream
# deliberately not). Deadlines are measured from the REPLAYED submit's
# own clock — the previous process's wall-clock budget is unknowable
# after a crash, and a fresh window errs on serving, not dropping.
_REPLAY_FIELDS = (
    "max_new_tokens", "do_sample", "temperature", "top_k", "top_p",
    "repetition_penalty", "eos_token_id", "queue_deadline_s", "deadline_s",
)


class RequestJournal:
    """Append-only JSONL journal; thread-safe (submit can come from any
    request thread while the engine thread records completions)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def _append(self, obj: dict) -> None:
        line = json.dumps(obj, separators=(",", ":"))
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def record_submit(self, req) -> None:
        entry = {"op": "submit", "rid": req.rid, "prompt": list(req.prompt)}
        for f in _REPLAY_FIELDS:
            v = getattr(req, f)
            if v is not None:
                entry[f] = v
        self._append(entry)

    def record_done(self, rid: int) -> None:
        self._append({"op": "done", "rid": rid})

    def close(self) -> None:
        with self._lock:
            self._f.close()

    @staticmethod
    def scan(path: str) -> tuple[list[dict], int]:
        """Parse a journal file -> (submit entries with no done marker,
        in submission order; highest rid seen). A truncated TRAILING line
        (the crash-mid-append case this journal must expect) is skipped
        with a warning; undecodable interior lines are skipped with a
        louder warning (they mean corruption beyond a torn tail). Either
        way recovery proceeds — a damaged line must never block replay of
        the intact entries around it."""
        if not os.path.exists(path):
            return [], -1
        submits: dict[int, dict] = {}
        max_rid = -1
        # one-line lookbehind instead of readlines(): a long-lived
        # journal can be large and recovery must stream it. An
        # undecodable line is only a torn tail if NOTHING follows it.
        torn: Optional[tuple[int, str]] = None
        with open(path, "r", encoding="utf-8") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                if torn is not None:
                    warnings.warn(
                        f"{path}: skipping undecodable journal line "
                        f"{torn[0] + 1} (interior corruption): "
                        f"{torn[1][:60]!r}",
                        stacklevel=2,
                    )
                    torn = None
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    torn = (i, line)
                    continue
                rid = obj.get("rid")
                if not isinstance(rid, int):
                    continue  # malformed entry must not block recovery
                max_rid = max(max_rid, rid)
                if obj.get("op") == "submit" and isinstance(
                    obj.get("prompt"), list
                ):
                    submits[rid] = obj
                elif obj.get("op") == "done":
                    submits.pop(rid, None)
        if torn is not None:
            warnings.warn(
                f"{path}: skipping truncated trailing journal "
                f"line (crash mid-append): {torn[1][:60]!r}",
                stacklevel=2,
            )
        return list(submits.values()), max_rid

    @staticmethod
    def pending(path: str) -> list[dict]:
        return RequestJournal.scan(path)[0]

    @staticmethod
    def compact(path: str) -> None:
        """Atomic rewrite keeping only pending submits. Offline
        maintenance ONLY — the os.replace swaps the inode out from
        under any live engine's open append handle."""
        pending = RequestJournal.pending(path)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            for e in pending:
                f.write(json.dumps(e, separators=(",", ":")) + "\n")
        os.replace(tmp, path)


def replay(engine, entries: list[dict]) -> list:
    """Re-submit unfinished journaled entries into `engine` (fresh
    rids, no streams), superseding each old entry with a tombstone the
    moment its replacement submit is recorded. No truncate-first window:
    a crash mid-replay leaves every not-yet-resubmitted entry pending
    for the NEXT recovery. The crash window between a replacement's
    submit record and the old tombstone yields at-least-once semantics
    (a later recovery may replay that request twice), never loss.
    Requires the engine's rid counter to be seeded past every journaled
    rid (the engine does this at journal attach) so old-rid tombstones
    cannot collide with fresh submissions."""
    j = getattr(engine, "_journal", None)
    out = []
    for e in entries:
        kwargs = {f: e[f] for f in _REPLAY_FIELDS if f in e}
        out.append(engine.submit(e["prompt"], **kwargs))
        if j is not None:
            j.record_done(e["rid"])  # superseded by the new record
    return out
