"""Crash-recovery request journal for the serving engine.

The reference's serving stack restarts through its k8s job specs but
loses every in-flight request on a worker crash (the FastAPI worker's
queue and the PPModelWorker batch state are process-local,
reference serving/fastapi/model_worker.py:28-200). TPU serving gets a
first-class restart story instead: every accepted request is appended
to a JSONL journal, completions append a tombstone, and a fresh engine
replays the unfinished tail into `engine.recovered_requests` — pairing with
deploy/'s restartPolicy so a killed pod resumes its queue instead of
dropping it.

Format: one JSON object per line, followed by a tab and the crc32 of the
JSON bytes (hex, 8 chars):
  {"op": "submit", "rid": 7, "prompt": [...], "max_new_tokens": 64, ...}\t1a2b3c4d
  {"op": "done", "rid": 7}\t5e6f7a8b

The crc suffix detects INTERIOR corruption (bit rot inside a record that
may even still parse as JSON) per-record — before it, only the
torn-trailing-line crash case was detectable. Compact JSON never
contains a raw tab, so the split is unambiguous; checksum-less lines
from pre-crc journals parse exactly as before (backward compatible).

A request is pending iff its last submit has no matching done. Replayed
requests get NEW rids (each old entry is superseded by a tombstone once
its replacement is recorded), and streaming consumers are not
resurrected — a replayed request completes as a plain buffered request
retrievable via the API server's GET /recovered.

On engine attach the journal is COMPACTED first (scan → rewrite holding
only the pending submits, through the atomic tmp+fsync+rename protocol)
— tombstoned pairs and corrupt lines stop accumulating across restarts,
and the rewrite happens strictly before the append handle opens, so the
live-inode hazard of mid-flight compaction never arises.
"""

from __future__ import annotations

import json
import os
import re
import threading
import warnings
import zlib
from typing import Optional

_CRC_RE = re.compile(r"^[0-9a-f]{8}$")


def crc_line(body: str) -> str:
    """`<body>\\t<crc32 hex>` — the journal's wire discipline, shared
    with the training supervisor's event log (train/supervisor.py) so
    the two line formats cannot drift."""
    return f"{body}\t{_crc_of(body)}"


def split_crc_line(line: str):
    """Inverse of :func:`crc_line`: (body, verdict) where verdict is
    True (crc present and matches), False (present, mismatch — bit
    rot), or None (no crc suffix: a legacy or torn line; the body is
    the whole line)."""
    body, sep, tail = line.rpartition("\t")
    if sep and _CRC_RE.fullmatch(tail):
        return body, _crc_of(body) == tail
    return line, None


def _crc_of(body: str) -> str:
    return f"{zlib.crc32(body.encode('utf-8')) & 0xFFFFFFFF:08x}"


_crc_line = crc_line

# sampling/stop/deadline fields that survive a restart (stream
# deliberately not). Deadlines are measured from the REPLAYED submit's
# own clock — the previous process's wall-clock budget is unknowable
# after a crash, and a fresh window errs on serving, not dropping.
_REPLAY_FIELDS = (
    "max_new_tokens", "do_sample", "temperature", "top_k", "top_p",
    "repetition_penalty", "eos_token_id", "queue_deadline_s", "deadline_s",
    # the named LoRA adapter (serving/adapters.py): a replayed tenant
    # request must decode with ITS fine-tune, not the shared base — the
    # registry re-resolves the name at the successor's admission
    "adapter",
)


class RequestJournal:
    """Append-only JSONL journal; thread-safe (submit can come from any
    request thread while the engine thread records completions)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")

    def _append(self, obj: dict) -> None:
        line = _crc_line(json.dumps(obj, separators=(",", ":")))
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def record_submit(self, req) -> None:
        entry = {"op": "submit", "rid": req.rid, "prompt": list(req.prompt)}
        for f in _REPLAY_FIELDS:
            v = getattr(req, f)
            if v is not None:
                entry[f] = v
        self._append(entry)

    def record_done(self, rid: int) -> None:
        self._append({"op": "done", "rid": rid})

    def close(self) -> None:
        with self._lock:
            self._f.close()

    @staticmethod
    def scan(path: str, stats: Optional[dict] = None) -> tuple[list[dict], int]:
        """Parse a journal file -> (submit entries with no done marker,
        in submission order; highest rid seen). A truncated TRAILING line
        (the crash-mid-append case this journal must expect) is skipped
        with a warning; undecodable interior lines and per-line crc32
        mismatches ANYWHERE are skipped with a louder warning (they mean
        corruption beyond a torn tail). Either way recovery proceeds — a
        damaged line must never block replay of the intact entries
        around it.

        `stats`, when given, receives `corrupt_lines` — the count of
        interior-undecodable + crc-mismatched lines (NOT the expected
        torn tail); the engine exports it as
        `bigdl_tpu_journal_corrupt_lines_total`."""
        if stats is not None:
            stats.setdefault("corrupt_lines", 0)
        if not os.path.exists(path):
            return [], -1

        def corrupt(n: int = 1) -> None:
            if stats is not None:
                stats["corrupt_lines"] += n

        submits: dict[int, dict] = {}
        max_rid = -1
        # one-line lookbehind instead of readlines(): a long-lived
        # journal can be large and recovery must stream it. An
        # undecodable line is only a torn tail if NOTHING follows it.
        torn: Optional[tuple[int, str]] = None
        with open(path, "r", encoding="utf-8") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                if torn is not None:
                    corrupt()
                    warnings.warn(
                        f"{path}: skipping undecodable journal line "
                        f"{torn[0] + 1} (interior corruption): "
                        f"{torn[1][:60]!r}",
                        stacklevel=2,
                    )
                    torn = None
                # crc-suffixed line (compact JSON never holds a raw tab,
                # so the split is unambiguous). A torn tail can never
                # masquerade here: truncation eats the crc digits first,
                # so a full 8-hex suffix means the line was written
                # whole — a mismatch is bit rot, torn-position or not.
                body, ok = split_crc_line(line)
                if ok is False:
                    corrupt()
                    warnings.warn(
                        f"{path}: skipping journal line {i + 1} with "
                        f"crc32 mismatch (interior corruption): "
                        f"{body[:60]!r}",
                        stacklevel=2,
                    )
                    continue
                if ok:
                    line = body
                try:
                    obj = json.loads(line)
                except json.JSONDecodeError:
                    torn = (i, line)
                    continue
                rid = obj.get("rid")
                if not isinstance(rid, int):
                    continue  # malformed entry must not block recovery
                max_rid = max(max_rid, rid)
                if obj.get("op") == "submit" and isinstance(
                    obj.get("prompt"), list
                ):
                    submits[rid] = obj
                elif obj.get("op") == "done":
                    submits.pop(rid, None)
        if torn is not None:
            warnings.warn(
                f"{path}: skipping truncated trailing journal "
                f"line (crash mid-append): {torn[1][:60]!r}",
                stacklevel=2,
            )
        return list(submits.values()), max_rid

    @staticmethod
    def pending(path: str) -> list[dict]:
        return RequestJournal.scan(path)[0]

    @staticmethod
    def compact(path: str, entries: Optional[list] = None) -> None:
        """Atomic rewrite keeping only pending submits (tombstoned pairs
        and corrupt lines dropped; every surviving line crc-suffixed),
        through the tmp+fsync+rename protocol. Startup or offline
        maintenance ONLY — the os.replace swaps the inode out from under
        any live engine's open append handle. Pass `entries` (a prior
        scan's pending list) to skip the rescan the engine already did."""
        if not os.path.exists(path):
            return
        if entries is None:
            entries = RequestJournal.pending(path)
        from bigdl_tpu.utils.durability import atomic_write

        def write(f) -> None:
            for e in entries:
                body = json.dumps(e, separators=(",", ":"))
                f.write((_crc_line(body) + "\n").encode("utf-8"))

        atomic_write(path, write)


def replay(engine, entries: list[dict]) -> list:
    """Re-submit unfinished journaled entries into `engine` (fresh
    rids, no streams), superseding each old entry with a tombstone the
    moment its replacement submit is recorded. No truncate-first window:
    a crash mid-replay leaves every not-yet-resubmitted entry pending
    for the NEXT recovery. The crash window between a replacement's
    submit record and the old tombstone yields at-least-once semantics
    (a later recovery may replay that request twice), never loss.
    Requires the engine's rid counter to be seeded past every journaled
    rid (the engine does this at journal attach) so old-rid tombstones
    cannot collide with fresh submissions."""
    j = getattr(engine, "_journal", None)
    out = []
    for e in entries:
        kwargs = {f: e[f] for f in _REPLAY_FIELDS if f in e}
        out.append(engine.submit(e["prompt"], **kwargs))
        if j is not None:
            j.record_done(e["rid"])  # superseded by the new record
    return out
