"""Slot-based continuous-batching inference engine.

TPU-native re-design of the reference's serving scheduler
(`PPModelWorker.process_step`, pipeline_parallel.py:482-929 in
/root/reference: dynamic batching with `max_num_seqs`, split prefill,
per-rank p2p hops; and `serving/fastapi/model_worker.py:28-200`'s async
queue loop). Here the whole batch lives in ONE static-shape XLA program:

- a fixed pool of `n_slots` decode slots shares one KV cache with
  **per-row write positions** (kvcache.KVCache with pos: [B]);
- prefill runs per request on bucketed lengths (its own small cache),
  then a jitted `insert` copies the prompt KV into the slot's rows —
  so a new request joins mid-flight without recompiling or disturbing
  running rows (the reference's "dynamic batching" without its Python
  per-step re-batching);
- one jitted `decode_step` advances every active slot one token and
  samples on device; idle slots compute masked garbage (the static-shape
  price, paid instead of recompilation).

The host-side loop (`step()`) only moves tokens in/out and does
bookkeeping — the reference's asyncio request queue maps onto it
directly (serving/api_server.py).
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import itertools
import queue
import threading
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import kvcache
from bigdl_tpu.generate import GenerationConfig, sample_token_per_row
from bigdl_tpu.models.config import ModelConfig
from bigdl_tpu.serving.faults import NULL_INJECTOR, FaultError
from bigdl_tpu.serving.metrics import Histogram
from bigdl_tpu.utils import round_up


@dataclasses.dataclass
class Request:
    rid: int
    prompt: list[int]
    max_new_tokens: int = 64
    # per-request sampling (None = engine default). These become traced
    # per-slot tensors in the decode step, so two concurrent requests can
    # sample with different temperatures in the same XLA program.
    do_sample: Optional[bool] = None
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    repetition_penalty: Optional[float] = None
    eos_token_id: Optional[int] = None
    # multi-tenant LoRA: the named adapter this request decodes with
    # (serving/adapters.py; None = the shared base). Resolved +
    # refcounted at admission; applied as a batched epilogue on the
    # shared fused dequant-GEMM (docs/serving.md §7).
    adapter: Optional[str] = None
    # filled by the engine
    out_tokens: list[int] = dataclasses.field(default_factory=list)
    # chosen-token logprob per emitted token (log softmax of the model's
    # pre-filtering distribution — OpenAI "logprobs" semantics)
    out_logprobs: list[float] = dataclasses.field(default_factory=list)
    # when the engine runs with logprobs_top_k=N: per emitted token, the
    # N most likely {token_id: logprob} alternatives
    out_top_logprobs: list[dict] = dataclasses.field(default_factory=list)
    done: bool = False
    finish_reason: str = ""  # "stop" (EOS) | "length" (budget) |
    # "invalid" (rejected at submit — over-long prompt) | "error" |
    # "shed" (overload: queue bound / queue deadline — retryable) |
    # "timeout" (per-request deadline expired mid-flight)
    error: Optional[str] = None
    # which admission limit shed the request ("queue_full" |
    # "queue_deadline") — structured so the HTTP layer's 429-vs-503
    # choice never depends on parsing the human-readable error text
    shed_kind: Optional[str] = None
    stream: Optional[queue.SimpleQueue] = None  # receives (token|None=EOS)
    # overload controls (None = engine default): how long the request may
    # wait for a slot, and its total wall-clock budget from submit
    queue_deadline_s: Optional[float] = None
    deadline_s: Optional[float] = None
    submit_ts: float = 0.0  # stamped by submit()
    admit_ts: Optional[float] = None  # first admission (pre-prefill)
    preemptions: int = 0  # times this request was swapped to host RAM
    # ---- lifecycle timing (obs/tracing.py; engine clock domain) ----
    first_token_ts: Optional[float] = None
    last_token_ts: Optional[float] = None
    preempt_ts: Optional[float] = None  # set while parked in host RAM
    preempted_s: float = 0.0  # total seconds spent parked (all swaps)


@dataclasses.dataclass
class _Slot:
    req: Optional[Request] = None
    remaining: int = 0
    eos: Optional[int] = None  # resolved per-request EOS id
    seq: int = 0  # admission order — the preemption victim policy's age
    # pos at the last swap-in; -1 = never preempted. A slot that cannot
    # extend AND has emitted nothing since its resume proves the pool
    # cannot support it (self-preempting again would livelock).
    resumed_pos: int = -1
    # decode-window trace state: tokens since the last emitted "decode"
    # span and that window's start timestamp (obs/tracing.py)
    t_win: float = 0.0
    n_win: int = 0


@dataclasses.dataclass
class _Preempted:
    """A request parked in host RAM: everything needed to resume decode
    bit-exactly — the KV blob plus the slot-side sampling/progress state
    that normally lives in the engine's per-slot arrays."""

    req: Request
    cur: int  # last emitted token (next decode input)
    remaining: int
    eos: Optional[int]
    pos: int  # tokens written (prompt + emitted)
    start: int  # dense left-pad offset (0 for paged)
    seq: int  # original admission age (kept: resumed requests stay old)
    temp: float
    topk: int
    topp: float
    dosample: bool
    penalty: float
    seen: Any  # [V] bool host row (repetition-penalty state)
    blob: Any  # kvpaged.HostKVPages | dense (k, v, ks, vs) tuple
    n_pages: int = 0  # paged: pages to reallocate on resume


@dataclasses.dataclass
class _PrefillState:
    """A request mid-chunked-prefill: it owns its slot and its fully
    allocated page table, but `active` stays False (no decode) and the
    engine's block-table row stays pointed at the scratch page until
    the last chunk lands — idle-slot garbage decode writes must never
    reach the half-filled (possibly shared) real pages. Chunks write
    through `row` directly (the jitted prefill takes its own block-
    table argument)."""

    req: Request
    slot: int
    row: "np.ndarray"  # the slot's REAL block-table row
    written: int  # prompt tokens whose KV is in the pool (incl. cache
    # hits + the sub-page copy)
    path: list  # the matched radix nodes (root-first) — kept so the
    # final chunk registers under them without re-walking the tree;
    # they cannot be evicted meanwhile (the slot holds their pages)
    chunk: int  # token budget per chunk


class InferenceEngine:
    """model: a TpuModel (api.py). Sampling params (do_sample /
    temperature / top-k / top-p / eos) are PER REQUEST: they ride the
    decode step as traced per-slot tensors, so concurrent requests with
    different configs share one compiled program. The engine-level
    GenerationConfig only provides defaults."""

    def __init__(
        self,
        model,
        n_slots: int = 8,
        max_len: int = 1024,
        gen: Optional[GenerationConfig] = None,
        seed: int = 0,
        paged: bool = False,
        page_size: int = 64,
        n_pages: Optional[int] = None,
        speculative: bool = False,
        draft_params=None,
        draft_k: int = 4,
        adaptive_draft: bool = False,
        truncate_prompts: bool = False,  # opt-in: keep over-long tails
        logprobs_top_k: int = 0,  # also return the N most likely
        # alternatives per emitted token (OpenAI top_logprobs); static
        # so the top-k pass compiles only into engines that opt in
        quantize_kv: bool = False,
        prefill_chunk_tokens: Optional[int] = None,  # paged only: split
        # prompt prefill into chunks of at most this many tokens and
        # advance AT MOST ONE chunk of ONE prefilling request per
        # step() — a 32k prompt arriving mid-decode then bounds the
        # running batch's inter-token stall by one chunk instead of one
        # prompt (docs/serving.md §6). None = monolithic prefill.
        journal: Optional[str] = None,
        # ---- overload protection (docs/serving.md) ----
        max_queue: Optional[int] = None,  # bound on waiting submits;
        # over-capacity submits fail fast with finish_reason="shed"
        queue_deadline_s: Optional[float] = None,  # default max wait for
        # a slot; expired-in-queue requests are shed, not served late
        deadline_s: Optional[float] = None,  # default total wall-clock
        # budget per request; expiry mid-decode finishes "timeout"
        preemption: bool = True,  # page-pool exhaustion mid-decode swaps
        # a victim's KV to host RAM and requeues it instead of silently
        # truncating its output with "length"
        preemption_policy: str = "youngest",  # victim choice: "youngest"
        # (least progress lost, default) or "oldest"
        faults: Optional[Any] = None,  # FaultInjector (serving/faults.py);
        # None = the shared inert injector (zero-cost hooks)
        adapters: Optional[Any] = None,  # AdapterRegistry
        # (serving/adapters.py): requests may name a LoRA adapter and
        # decode with it applied as a batched unquantized epilogue on
        # the shared base — one forward serves a heterogeneous adapter
        # batch (docs/serving.md §7). None = adapter= submits are
        # rejected as invalid.
        # ---- observability (docs/observability.md) ----
        tracer: Optional[Any] = None,  # obs.tracing.TraceRecorder; spans
        # recorded only while tracer.enabled (off = one attr check)
        request_log: Optional[str] = None,  # JSONL path: one derived-
        # timings record per finished request (crc-suffixed lines)
        trace_decode_every: int = 8,  # decode tokens coalesced per span
        clock: Callable[[], float] = time.time,  # every lifecycle
        # timestamp (deadlines, spans, histograms) flows through this —
        # the simulated-clock benchmark drives the engine with a fake one
    ):
        self.model = model
        # clock + observability sinks FIRST: submit()/journal replay at
        # the end of __init__ already stamp timestamps and record finishes
        self._clock = clock
        self.tracer = tracer
        self.trace_decode_every = max(int(trace_decode_every), 1)
        self._request_log = None
        if request_log is not None:
            from bigdl_tpu.obs.tracing import RequestLog

            self._request_log = RequestLog(request_log)
        self._t_start = clock()
        # terminal finish_reason -> count (metrics.py renders the family;
        # handler threads insert via _note_finish, the scrape thread
        # snapshots under the same lock)
        # guarded-by: _stat_lock
        self.finish_reasons: "collections.defaultdict[str, int]" = \
            collections.defaultdict(int)
        self._journal = None  # attached at the END of __init__ (it
        # replays the previous process's unfinished tail, which needs
        # the queue and rid counter live)
        self.recovered_requests: list[Request] = []
        self.config: ModelConfig = model.config
        self.n_slots = n_slots
        self.max_len = max_len
        self.gen = gen or GenerationConfig()
        # paged KV (kvpaged.py): pages allocated on demand + refcounted
        # prefix cache, so the pool can be smaller than slots*max_len and
        # identical prompt prefixes share storage AND prefill compute
        # (the reference's paged attention + prefix caching live in its
        # vLLM fork, vllm/xpu/)
        if logprobs_top_k and speculative:
            # checked BEFORE any pool allocation / AOT compile below —
            # failing after seconds of compile and GBs of HBM is hostile
            raise NotImplementedError(
                "logprobs_top_k is not wired through the speculative "
                "verify round yet; use speculative=False"
            )
        self.paged = paged
        # fp8 KV storage for the shared pool (dense or paged): halves KV
        # HBM capacity + traffic, the reference's fp8 kv-cache lever
        self.quantize_kv = quantize_kv
        # families with their own cache serve through either (a) the
        # generic dataclass insert path when they declare SERVABLE_CACHE
        # (MLA's latent — flat [L, B, S, ...] fields with real pos/start;
        # models/deepseek.py), or (b) their own engine_pool/engine_insert
        # adapter when the cache has nested pools or property pos
        # (rwkv recurrent state, yuan localized-filter hiddens, mllama
        # cross-attention; the generic path would silently corrupt them).
        fam = model.family
        self._family_cache = None
        self._family_pool = getattr(fam, "engine_pool", None)
        self._family_insert = getattr(fam, "engine_insert", None)
        if (self._family_pool is None) != (self._family_insert is None):
            # half an adapter would silently mix the custom and generic
            # cache paths (e.g. a pool without per-row pos fed through
            # the generic dataclass insert)
            raise TypeError(
                f"{model.config.model_type}: engine_pool and engine_insert "
                "must be defined together"
            )
        if hasattr(fam, "init_cache"):
            custom = (self._family_pool is not None
                      and self._family_insert is not None)
            if not custom and not getattr(fam, "SERVABLE_CACHE", False):
                raise NotImplementedError(
                    f"the serving engine does not support "
                    f"{model.config.model_type}'s cache layout yet; use "
                    "TpuModel.generate()"
                )
            self._family_cache = fam.init_cache
        if paged and self._family_cache is not None:
            raise NotImplementedError(
                f"paged serving is not available for "
                f"{model.config.model_type}: its cache is not a KV pool"
            )
        if quantize_kv and self._family_cache is not None:
            # the family init_cache/engine_pool signatures don't thread
            # quantize_kv; silently serving bf16 KV would misreport the
            # memory footprint the caller asked for (ADVICE r04)
            raise NotImplementedError(
                f"quantize_kv is not wired for "
                f"{model.config.model_type}'s family cache; use "
                "quantize_kv=False"
            )
        self.page_size = page_size
        # physical reserve past max_len: a speculative verify round writes
        # draft_k tokens at pos..pos+K-1 before rolling back; a request
        # whose decode window ends flush with max_len would otherwise lose
        # those writes (out-of-bounds scatters drop silently and the
        # emitted tokens attend with missing keys — ADVICE r04). Extra
        # PHYSICAL slots keep outputs byte-identical to plain serving,
        # unlike shrinking the logical window (which re-truncates prompts).
        self._reserve = max(draft_k - 1, 0) if speculative else 0
        self.max_pages_per_row = -(-(max_len + self._reserve) // page_size)
        # +1: physical page 0 is the reserved scratch sink, so the default
        # pool still covers every slot at full logical length
        self.n_pages = n_pages or n_slots * self.max_pages_per_row + 1
        if prefill_chunk_tokens is not None:
            if not paged:
                raise ValueError(
                    "prefill_chunk_tokens requires paged=True (chunks "
                    "write straight into the shared page pool)"
                )
            if prefill_chunk_tokens < 1:
                raise ValueError(
                    f"prefill_chunk_tokens must be >= 1, got "
                    f"{prefill_chunk_tokens}"
                )
            if speculative:
                # the draft pool's _admit_draft prefill is monolithic
                # (full prompt through the draft model at activation) —
                # it would break the one-chunk stall bound this knob
                # promises. Refuse honestly instead of jittering
                # silently; chunking the draft admission is the
                # follow-up.
                raise NotImplementedError(
                    "prefill_chunk_tokens is not wired through the "
                    "speculative draft admission yet; use "
                    "speculative=False or monolithic prefill"
                )
        self.prefill_chunk_tokens = prefill_chunk_tokens
        # prefill invocations (each chunk is one; a monolithic prefill
        # counts 1) — bigdl_tpu_prefill_chunks_total
        self.prefill_chunks = 0
        # the at-most-one request currently mid-chunked-prefill: it
        # holds its slot and pages but is NOT decoded (active stays
        # False) until its last chunk lands. Engine-thread only.
        self._prefilling: Optional[_PrefillState] = None
        if paged:
            # physical page 0 is the scratch sink: idle slots still run
            # the decode step (static-shape price) and their masked
            # garbage writes go through their block tables — released
            # slots point every entry at page 0 so they can never corrupt
            # pages reallocated to live requests
            from bigdl_tpu import kvpaged
            from bigdl_tpu.serving.radix import RadixPrefixCache

            # refcounted page accounting: one hold per slot block-table
            # entry + one per cached radix node (kvpaged.PagePool);
            # _free_pages/_page_ref stay as live views of the pool's
            # lists (metrics.py and the sim driver read them)
            self._pool = kvpaged.PagePool(self.n_pages)
            self._free_pages = self._pool.free
            self._page_ref = self._pool.ref
            # radix-tree prefix cache (serving/radix.py): full-page
            # descent + mid-page divergence match + leaf-first LRU
            # eviction; replaced the flat tuple(prefix)-hash cache
            self.radix = RadixPrefixCache(page_size, self._pool)
            self._slot_pages: list[list[int]] = [[] for _ in range(n_slots)]
            self._slot_written: list[int] = [0] * n_slots  # logical slots covered
            self.prefix_hits = 0
            # sub-page sharing: cached-page KV copied instead of
            # re-prefilled when a prefix diverges mid-page
            self.prefix_partial_hits = 0
            self.prefix_tokens_reused = 0
            self.prefix_evictions = 0  # radix leaves dropped for pages
            self._bt_host = np.zeros(
                (n_slots, self.max_pages_per_row), np.int32
            )
            self._bt_dirty = True
            self._slot_pos = [0] * n_slots  # host mirror of cache.pos
        self._rng = jax.random.PRNGKey(seed)
        # queue.Queue (not SimpleQueue): the queue-deadline sweep filters
        # the backing deque in place under .mutex
        self._queue: "queue.Queue[Request]" = queue.Queue()
        self._slots = [_Slot() for _ in range(n_slots)]
        # rids start at 1: a request's trace track is tid=rid, and tid 0
        # is the engine track (decode_step spans, batch counter) — a
        # rid-0 request would interleave its lifecycle spans with the
        # engine's and break per-track monotonic nesting
        self._rid = itertools.count(1)
        # model sharded via TpuModel.to_mesh(): all jitted steps run SPMD
        # under the mesh, with the KV pool sharded over kv heads ('tp')
        self._mesh = getattr(model, "mesh", None)

        self.cache = self._make_pool()
        self.cur = jnp.zeros((n_slots,), jnp.int32)  # last token per slot
        self.active = np.zeros((n_slots,), bool)  # host-side mask
        # per-slot sampling params (host mirrors, shipped traced each step)
        g = self.gen
        self._temp = np.full((n_slots,), g.temperature, np.float32)
        self._topk = np.full((n_slots,), g.top_k or 0, np.int32)
        self._topp = np.full((n_slots,), g.top_p if g.top_p is not None else 1.0,
                             np.float32)
        self._dosample = np.full((n_slots,), g.do_sample, bool)
        self._penalty = np.full((n_slots,), 1.0, np.float32)
        # per-slot seen-token masks for the HF repetition penalty
        # (reference xe_addons.repetition_penalty_logits_process_inplaced);
        # the all-1.0 common case skips the rewrite via a lax.cond in
        # _decode_impl
        self.seen = jnp.zeros((n_slots, self.config.vocab_size), jnp.bool_)

        # ---- multi-tenant LoRA adapters (serving/adapters.py) ----
        self.adapters = adapters
        # rid -> AdapterEntry: ONE reference per in-flight request that
        # resolved an adapter (held across preemption parking and the
        # paged OOM-retry wait; released at the terminal finish in
        # _note_finish — the kvpaged.PagePool one-hold-per-holder rule)
        self._adapter_refs: dict[int, Any] = {}
        self._slot_adapter: list[Optional[Any]] = [None] * n_slots
        # the decode step's batched per-slot adapter tree, rebuilt only
        # when a slot's adapter assignment changes (not per token)
        self._blora: Optional[dict] = None
        self._blora_dirty = True
        # rank + target set of the adapter the CURRENT prefill dispatch
        # serves (0/() = base-only) — observability the sim's cost
        # wrappers price
        self._last_prefill_rank = 0
        self._last_prefill_targets: tuple = ()
        # unified HBM paging (docs/serving.md §7): resident adapters'
        # (A, B) leaves live in pages drawn from the SAME PagePool as
        # KV — one device budget. Under page pressure the allocator's
        # escalation is radix leaf -> holder-free adapter page-out ->
        # preemption (_alloc_page); _gather_blora reads the pages
        # instead of re-transferring host weights per assignment change
        self._pager = None
        if adapters is not None and paged and self._family_pool is None:
            from bigdl_tpu import kvpaged
            from bigdl_tpu.serving.adapters import AdapterPager

            self._adapter_store = kvpaged.AdapterPageStore(
                self.n_pages, kvpaged.kv_page_nbytes(self.cache)
            )
            self._pager = AdapterPager(
                self._adapter_store, self._pool, self._alloc_page,
                faults=faults,
            )

        # forward_fn: the family forward, or the pipeline step when the
        # mesh has a pp axis (api.TpuModel.forward_fn)
        fwd = getattr(model, "forward_fn", None) or model.family.forward
        if adapters is not None:
            # speculative + adapters: the draft scan stays base/dense
            # (advisory — any draft content yields the same emitted
            # tokens; an adapter-shifted target only lowers acceptance)
            # while the VERIFY forward applies the batched adapter tree
            # at the draft's proposed positions, so emitted tokens match
            # non-speculative adapter decode exactly (_spec_decode_impl)
            import inspect

            try:
                fwd_params = inspect.signature(fwd).parameters
            except (TypeError, ValueError):  # pragma: no cover - exotic
                fwd_params = {"lora": None}
            if "lora" not in fwd_params:
                raise NotImplementedError(
                    f"{model.config.model_type}'s forward has no lora= "
                    "epilogue path; adapter serving needs a llama-family "
                    "forward"
                )
        self._decode = self._with_mesh(jax.jit(
            functools.partial(self._decode_impl, fwd),
            donate_argnames=("cache", "seen"),
        ))
        self._prefill = self._with_mesh(jax.jit(
            functools.partial(self._prefill_impl, fwd),
            static_argnames=("bucket",),
        ))
        self._insert = self._with_mesh(jax.jit(
            self._insert_impl, donate_argnames=("cache",)
        ))
        self._paged_prefill = self._with_mesh(jax.jit(
            functools.partial(self._paged_prefill_impl, fwd),
            donate_argnames=("k", "v", "ks", "vs"),
        ))
        self._copy_page = self._with_mesh(jax.jit(
            self._copy_page_impl, donate_argnames=("cache",)
        ))
        # --- in-engine speculative decoding (reference serves it through
        # ipex_llm_worker.py:72-99; SURVEY §7 names "continuous batching +
        # speculative interaction" a hard part). Slot-pool design: a
        # SECOND KV pool for the draft model, a scan of per-row greedy
        # draft steps, then ONE batched verify forward over the shared
        # target pool; per-row `pos` makes per-slot acceptance rollback a
        # vector subtraction. Greedy slots emit the target's greedy
        # tokens — byte-identical to non-speculative serving; sampling
        # slots accept drafts by rejection sampling (exact output law);
        # repetition-penalty slots ride along accepting 0 drafts (their
        # position-0 token is the regular sampler's).
        self.speculative = speculative
        self.draft_k = draft_k
        self.dcache = None
        self._draft_params = draft_params
        if speculative:
            if draft_k < 2:
                # K-1 draft tokens are verifiable; K=1 would pay a draft
                # forward whose token can never be accepted
                raise ValueError(f"draft_k must be >= 2, got {draft_k}")
            if self._family_pool is not None:
                # engine_pool adapters (rwkv recurrence, yuan filter
                # state, mllama cross-attn) have nested pools / property
                # pos — the vector rollback below cannot express their
                # crop. SERVABLE_CACHE dataclasses (MLA latents) carry
                # real per-row pos and speculate like the standard pool.
                raise NotImplementedError(
                    f"speculative serving is not wired for "
                    f"{model.config.model_type}'s custom cache adapter"
                )
            if draft_params is None:
                self._draft_params = model.self_draft_params()
            # the draft pool is ALWAYS dense (even when the target pool is
            # paged): the draft model needs full prompt context, and a
            # dense [slots, max_len] draft pool keeps the verify-round
            # rollback a per-row pos subtraction in both pools
            self.dcache = self._make_pool(force_dense=True)
            spec_jit = jax.jit(
                functools.partial(self._spec_decode_impl, fwd),
                static_argnums=(0,),  # k_draft: ladder of compiled programs
                donate_argnames=("cache", "dcache", "seen"),
            )
            self._spec_decode = self._with_mesh(spec_jit)
            self.spec_rounds = 0  # verify rounds run
            self.spec_emitted = 0  # tokens emitted by those rounds
            # adaptive draft length (reference speculative.py's adaptive
            # th_stop_draft tunes drafting from recent acceptance; a
            # static-K XLA program cannot stop mid-draft, so this
            # switches between a few compiled K programs instead)
            ks = {draft_k}
            if adaptive_draft:
                k_ = draft_k
                while k_ > 2:
                    k_ = max(2, k_ // 2)
                    ks.add(k_)
            self._k_ladder = sorted(ks)
            self._cur_k = draft_k
            self._accept_ema: Optional[float] = None
            self._spec_exec = None
            if adaptive_draft and adapters is None:
                # AOT-compile every ladder program NOW: the first ladder
                # switch must not stall in-flight streams on a
                # mid-serving XLA compile. lower() only reads avals (no
                # donation of the live pools); the compiled executables
                # stay valid across _reset_state (same shapes).
                import contextlib

                from bigdl_tpu.parallel._compat import set_mesh

                ctx = (set_mesh(self._mesh) if self._mesh is not None
                       else contextlib.nullcontext())
                args = (self.model.params, self._draft_params, self.cur,
                        self.cache, self.dcache, jax.random.PRNGKey(0),
                        jnp.asarray(self._temp), jnp.asarray(self._topk),
                        jnp.asarray(self._topp),
                        jnp.asarray(self._dosample), self.seen,
                        jnp.asarray(self._penalty))
                with ctx:
                    self._spec_exec = {
                        k_: spec_jit.lower(k_, *args).compile()
                        for k_ in self._k_ladder
                    }
        elif adaptive_draft:
            raise ValueError(
                "adaptive_draft steers the speculative draft length — "
                "pass speculative=True (CLI: --speculative) to enable it"
            )
        self.adaptive_draft = adaptive_draft
        self.truncate_prompts = truncate_prompts
        self.logprobs_top_k = logprobs_top_k
        self._waiting: Optional[Request] = None  # paged OOM retry slot
        # rid -> Request whose client went away (stop-string hit,
        # disconnect, server timeout): handler threads add, the engine
        # thread frees the slot at the top of its next step — no
        # cross-thread _finish races. The Request is kept (not just the
        # rid) so the reaper can prune entries that lost the race with a
        # normal finish; a bare rid set would grow forever in a
        # long-running server.
        self._cancelled: dict[int, Request] = {}

        # ---- overload protection state ----
        if preemption_policy not in ("youngest", "oldest"):
            raise ValueError(
                f"preemption_policy must be 'youngest' or 'oldest', "
                f"got {preemption_policy!r}"
            )
        self.max_queue = max_queue
        self.queue_deadline_s = queue_deadline_s
        self.deadline_s = deadline_s
        self.preemption = preemption
        self.preemption_policy = preemption_policy
        self._faults = faults if faults is not None else NULL_INJECTOR
        # graceful-shutdown latch (begin_drain): new submits shed with
        # kind "draining" (503 + Retry-After) while in-flight work runs
        # to completion. Plain bool store/read across threads — a submit
        # racing the latch lands at most one extra request in the drain.
        self._draining = False
        # accepted-but-unfinished request count (under _stat_lock): the
        # drain's completion signal. Structural emptiness (queue/slots/
        # parked) is NOT a substitute — a request mid-admission sits in
        # none of those containers for a moment, and a drain poll in
        # that window would declare an idle engine with work in hand.
        self._inflight = 0  # guarded-by: _stat_lock
        # True while fail_all tears down after an (injected) crash:
        # crash points must not re-fire inside the cleanup's _finish
        # calls or the cleanup itself dies and the engine thread hangs
        self._cleanup = False
        # serializes the max_queue check-then-put across handler threads
        # so the admission bound is exact, not best-effort
        self._admission_lock = threading.Lock()
        # guards counters bumped from handler threads AND the engine
        # thread (requests_shed, request_timeouts) — see _bump
        self._stat_lock = threading.Lock()
        # one deadline-bearing submit arms the per-step queue sweep for
        # the engine's lifetime; deployments that never set a deadline
        # never pay the O(queue) scan under queue.mutex each step
        self._deadlines_seen = (queue_deadline_s is not None
                                or deadline_s is not None)
        # preempted requests parked in host RAM, FIFO: the resume order.
        # Only the engine thread touches it.
        self._preempted: "collections.deque[_Preempted]" = collections.deque()
        # operator/server-initiated preemption (thread-safe, like cancel)
        self._preempt_requested: set[int] = set()
        self._seq = itertools.count(1)  # slot admission age
        # observability (serving/metrics.py renders these)
        self.preemptions = 0
        self.preemption_resumes = 0
        self.requests_shed = 0  # guarded-by: _stat_lock
        self.request_timeouts = 0  # guarded-by: _stat_lock
        self.requests_completed = 0
        self.journal_corrupt_lines = 0  # set at journal attach below
        self.queue_wait = Histogram()
        # phase-latency histograms (docs/observability.md): observed
        # unconditionally — metrics are always on, tracing is opt-in
        from bigdl_tpu.serving.metrics import FAST_BUCKETS

        self.ttft = Histogram()  # submit -> first emitted token
        self.itl = Histogram(buckets=FAST_BUCKETS)  # inter-token gap
        self.prefill_seconds = Histogram(buckets=FAST_BUCKETS)
        self.decode_step_seconds = Histogram(buckets=FAST_BUCKETS)
        # satellite (ISSUE 11): resume requeue time is its OWN family —
        # folding it into queue_wait would hide preemption stalls inside
        # the admission-wait signal operators alert on
        self.resume_wait = Histogram()
        # swap-in programs (swap-OUT is a plain device_get, no jit). The
        # donated cache makes the restore an in-place scatter. Family
        # caches (nested pools / property pos) have no row-swap story:
        # preemption is gated off for them.
        if self._family_cache is not None:
            self.preemption = False
        elif paged:
            from bigdl_tpu import kvpaged

            self._swap_in = self._with_mesh(jax.jit(
                kvpaged.swap_in_pages, donate_argnames=("cache",)
            ))
        else:
            self._dense_swap_in = self._with_mesh(jax.jit(
                kvcache.swap_in_row, donate_argnames=("cache",)
            ))

        # crash-recovery request journal (serving/journal.py): accepted
        # requests are appended as JSONL, completions tombstoned.
        # Attaching to an existing journal AUTO-REPLAYS the previous
        # process's unfinished tail (into self.recovered_requests) with
        # the rid counter seeded past every journaled rid — replay-first
        # is an engine invariant, not a per-caller dance, because a
        # fresh rid=0 tombstone would otherwise cancel the old pending
        # rid-0 entry and silently lose it.
        if journal is not None:
            from bigdl_tpu.serving.journal import RequestJournal, replay

            stats: dict = {}
            entries, max_rid = RequestJournal.scan(journal, stats=stats)
            # corrupt lines seen at attach (interior rot / crc
            # mismatches) — exported as
            # bigdl_tpu_journal_corrupt_lines_total
            self.journal_corrupt_lines = stats.get("corrupt_lines", 0)
            # startup compaction: rewrite the journal down to its
            # pending tail (tombstoned pairs and corrupt lines dropped,
            # atomic rename) BEFORE the append handle opens — the one
            # moment compaction cannot race a live writer. The rid
            # counter still seeds from the PRE-compaction max so a rid
            # whose lines were just dropped is never reissued into any
            # overlapping recovery window.
            RequestJournal.compact(journal, entries=entries)
            self._rid = itertools.count(max_rid + 1)
            self._journal = RequestJournal(journal)
            # replay bypasses the admission bound: every entry was ACCEPTED
            # by the previous process, and a shed here would erase its only
            # journal record (replay tombstones the old rid the moment the
            # replacement submit lands) — recovery must never shrink to
            # max_queue. No thread races: __init__ hasn't returned, so no
            # handler thread can interleave a live submit.
            bound, self.max_queue = self.max_queue, None
            try:
                self.recovered_requests = replay(self, entries)
            finally:
                self.max_queue = bound

    def _with_mesh(self, fn):
        if self._mesh is None:
            return fn

        def wrapped(*a, **k):
            from bigdl_tpu.parallel._compat import set_mesh

            with set_mesh(self._mesh):
                return fn(*a, **k)

        return wrapped

    def _make_pool(self, force_dense: bool = False):
        """The shared KV pool, per-row positions from the start (idle rows
        park at 0); sharded over kv heads when the model is on a mesh.
        force_dense: the speculative draft pool stays dense even when the
        target pool is paged."""
        cfg = self.config
        if self._family_pool is not None:
            return self._family_pool(cfg, self.n_slots, self.max_len)
        if self._family_cache is not None:
            cache = self._family_cache(cfg, self.n_slots, self.max_len)
            return dataclasses.replace(
                cache, pos=jnp.zeros((self.n_slots,), jnp.int32)
            )
        if self.paged and not force_dense:
            from bigdl_tpu import kvpaged

            return kvpaged.init_paged(
                cfg.num_hidden_layers, self.n_pages, self.page_size,
                cfg.num_key_value_heads, cfg.head_dim_, self.n_slots,
                self.max_pages_per_row, quantize_kv=self.quantize_kv,
            )
        cache = kvcache.init_cache(
            cfg.num_hidden_layers, self.n_slots, self.max_len + self._reserve,
            cfg.num_key_value_heads, cfg.head_dim_,
            quantize_kv=self.quantize_kv,
        )
        cache = dataclasses.replace(
            cache, pos=jnp.zeros((self.n_slots,), jnp.int32)
        )
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # layer axis over pp stages (when present), kv heads over tp
            pp = "pp" if "pp" in self._mesh.axis_names else None
            kv_sh = NamedSharding(self._mesh, P(pp, None, None, "tp", None))
            rep = NamedSharding(self._mesh, P())
            cache = dataclasses.replace(
                cache,
                k=jax.device_put(cache.k, kv_sh),
                v=jax.device_put(cache.v, kv_sh),
                pos=jax.device_put(cache.pos, rep),
                start=jax.device_put(cache.start, rep),
            )
        return cache

    # ---- jitted pieces ----------------------------------------------------

    def _prefill_impl(self, forward, params, tokens, start, bucket,
                      lora=None):
        """Single-request prefill on its own scalar-pos cache. `lora`
        is the request's rank-bucketed adapter tree (None = base): the
        prompt's KV and first-token logits must carry the adapter or
        decode parity with the offline-merged weights breaks at token
        one."""
        cfg = self.config
        if self._family_cache is not None:
            cache = self._family_cache(cfg, 1, bucket)
        else:
            cache = kvcache.init_cache(
                cfg.num_hidden_layers, 1, bucket, cfg.num_key_value_heads,
                cfg.head_dim_, quantize_kv=self.quantize_kv,
            )
        cache = dataclasses.replace(cache, start=start)
        kw = {} if lora is None else {"lora": lora}
        logits, cache = forward(
            cfg, params, tokens, cache, mode="prefill",
            last_logits_only=True, **kw
        )
        return logits[:, -1], cache

    def _insert_impl(self, cache, pcache, slot, pad):
        """Copy a prefilled request's KV (length `bucket`) into slot row at
        slots [0, bucket); per-row pos/start updated. Family caches (MLA
        latents) insert generically: every [L, B, ...] array field of the
        dataclass takes the prefill cache's row at the slot index.
        Families with nested/recurrent caches provide engine_insert."""
        if self._family_insert is not None:
            return self._family_insert(cache, pcache, slot, pad)
        if self._family_cache is not None:
            bucket = None
            upd = {}
            for f in dataclasses.fields(cache):
                v = getattr(cache, f.name)
                pv = getattr(pcache, f.name)
                if f.name in ("pos", "start"):
                    continue
                if isinstance(v, jax.Array) and v.ndim >= 2:
                    if bucket is None and v.ndim >= 3:
                        bucket = pv.shape[2]
                    idx = (0, slot) + (0,) * (v.ndim - 2)
                    upd[f.name] = jax.lax.dynamic_update_slice(
                        v, pv.astype(v.dtype), idx
                    )
            upd["pos"] = cache.pos.at[slot].set(bucket)
            upd["start"] = cache.start.at[slot].set(pad)
            return dataclasses.replace(cache, **upd)
        return kvcache.insert_row(cache, pcache, slot, pad)

    @staticmethod
    def _copy_page_impl(cache, src, dst):
        """Duplicate one physical page's KV (all layers) into another —
        the sub-page prefix-sharing copy (slots past the shared run are
        overwritten by the tail prefill or masked by pos)."""
        upd = {"k": cache.k.at[:, dst].set(cache.k[:, src]),
               "v": cache.v.at[:, dst].set(cache.v[:, src])}
        if cache.quantized:
            upd["k_scale"] = cache.k_scale.at[:, dst].set(cache.k_scale[:, src])
            upd["v_scale"] = cache.v_scale.at[:, dst].set(cache.v_scale[:, src])
        return dataclasses.replace(cache, **upd)

    def _paged_prefill_impl(self, forward, params, k, v, ks, vs, row_bt,
                            pos0, tokens, last_idx, lora=None):
        """Tail prefill for ONE slot, writing straight into the shared
        page pool (donated k/v): no dense mini-cache, no insert copy.
        tokens are RIGHT-padded to a bucket; last_idx selects the real
        last token's logits (pad writes land at slots >= pos and are
        overwritten by decode). `lora` = the request's rank-bucketed
        adapter tree (every chunk of a chunked prefill carries it)."""
        from bigdl_tpu import kvpaged

        cache = kvpaged.PagedKVCache(
            k=k, v=v, k_scale=ks, v_scale=vs, block_tables=row_bt, pos=pos0,
            start=jnp.zeros((1,), jnp.int32),
        )
        kw = {} if lora is None else {"lora": lora}
        logits, cache = forward(
            self.config, params, tokens, cache, mode="prefill", **kw
        )
        return (logits[0, last_idx], cache.k, cache.v, cache.k_scale,
                cache.v_scale)

    def _decode_impl(self, forward, params, cur, cache, key,
                     temp, topk, topp, dosample, seen, penalty,
                     lora=None):
        from bigdl_tpu.generate import apply_repetition_penalty

        # lora = the batched per-slot adapter tree (_gather_blora):
        # [L, B, rb, in]/[L, B, out, rb] leaves + a [B] scale, applied
        # as an einsum epilogue on each projection's fused dequant-GEMM
        # output (ops/linear.lora_epilogue) — adapter-less slots carry
        # zero-padded rows and a 0 scale, contributing exactly nothing
        kw = {} if lora is None else {"lora": lora}
        logits, cache = forward(
            self.config, params, cur[:, None], cache, mode="decode", **kw
        )
        last = logits[:, -1]
        # all-default batches (every penalty 1.0) skip the O(slots x V)
        # rewrite, mirroring sample_token_per_row's all-greedy guard
        step = jax.lax.cond(
            jnp.any(penalty != 1.0),
            lambda: apply_repetition_penalty(last, seen, penalty),
            lambda: last,
        )
        nxt = sample_token_per_row(step, key, temp, topk, topp, dosample)
        # chosen-token logprob without materializing [B, V] log-softmax:
        # gather the logit, subtract the row's logsumexp
        step32 = step.astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(step32, axis=-1)
        lp = (jnp.take_along_axis(step32, nxt[:, None], axis=-1)[:, 0]
              - lse)
        top = None
        if self.logprobs_top_k:  # static: compiles only when opted in
            tv, ti = jax.lax.top_k(step32, self.logprobs_top_k)
            top = (ti, tv - lse[:, None])
        seen = seen.at[jnp.arange(seen.shape[0]), nxt].set(True)
        return nxt, lp, top, cache, seen

    def _spec_decode_impl(self, forward, k_draft, params, dparams, cur, cache,
                          dcache, key, temp, topk, topp, dosample, seen,
                          penalty, lora=None):
        """One speculative round for the whole slot pool. Returns
        (choice [B, K], lp_all [B, K], n_acc [B], cur' [B], cache,
        dcache, seen): slot b emits choice[b, :n_acc[b]+1], with
        lp_all carrying each token's target logprob.

        Cache discipline (decode/speculative.py's crop, per-row): the
        draft scan advances dcache.pos by K and the verify forward
        advances cache.pos by K; both roll back to pos + n_acc + 1 — a
        vector op thanks to per-row positions. Entries above pos hold
        stale drafts that are masked out and overwritten next round.
        Acceptance caps at K-1 because the draft pool only holds KV for
        cur, d0..d_{K-2}.

        Acceptance rule per row: greedy rows match the target argmax
        (byte-identical to plain serving); sampling rows run rejection
        acceptance (exact sampling law, decode/speculative.py's
        rejection_accept); repetition-penalty rows accept 0."""
        from bigdl_tpu.generate import apply_repetition_penalty

        cfg = self.config
        K = k_draft  # static: one compiled program per ladder value

        def draft_step(carry, _):
            tok, dc = carry
            lg, dc = forward(cfg, dparams, tok[:, None], dc, mode="decode")
            nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
            return (nxt, dc), nxt

        (_, dcache), drafts = jax.lax.scan(
            draft_step, (cur, dcache), None, length=K
        )
        drafts = jnp.swapaxes(drafts, 0, 1)  # [B, K]

        verify_in = jnp.concatenate([cur[:, None], drafts[:, :K - 1]], axis=1)
        # adapter-aware verification: the TARGET forward applies the
        # batched per-slot adapter tree (the same one plain decode
        # uses), so accepted tokens follow the adapter-shifted target
        # law exactly — emitted tokens match non-speculative adapter
        # decode token-for-token. The draft above stays base/dense
        # (drafts are advisory: any draft content yields the same
        # output law, only the acceptance RATE moves)
        kw = {} if lora is None else {"lora": lora}
        tlogits, cache = forward(
            cfg, params, verify_in, cache, mode="prefill", **kw
        )
        tlogits = tlogits.astype(jnp.float32)
        greedy = jnp.argmax(tlogits, axis=-1).astype(jnp.int32)  # [B, K]

        # acceptance per decode mode: greedy rows match the target's
        # argmax (byte-identical to plain serving); sampling rows run
        # rejection acceptance against the full per-position sampling
        # distribution (exact output law — decode/speculative.py);
        # repetition-penalty rows accept 0 and take the penalty-adjusted
        # sampler token at position 0 (their distribution depends on
        # tokens emitted earlier in the same round)
        from bigdl_tpu.decode.speculative import rejection_accept
        from bigdl_tpu.generate import filter_logits_per_row

        pen1 = penalty == 1.0
        row_greedy = ~dosample & pen1
        row_sampled = dosample & pen1
        k_acc, k_pen = jax.random.split(key)

        def accept_mixed():
            probs = jax.nn.softmax(
                filter_logits_per_row(tlogits, temp, topk, topp), axis=-1
            )
            return rejection_accept(
                k_acc, probs, drafts, greedy, row_greedy, row_sampled
            )

        def accept_greedy_only():
            # all-greedy pools (the common serving case) skip the two
            # full [B, K, V] sorts + softmax of the filtered-probs path
            acc = (drafts[:, : K - 1] == greedy[:, : K - 1]) \
                & row_greedy[:, None]
            n = jnp.sum(jnp.cumprod(acc.astype(jnp.int32), axis=1), axis=1)
            return n, jnp.take_along_axis(greedy, n[:, None], axis=1)[:, 0]

        n_acc, extra = jax.lax.cond(
            jnp.any(row_sampled), accept_mixed, accept_greedy_only
        )

        def penalty_sample():
            step0 = apply_repetition_penalty(tlogits[:, 0], seen, penalty)
            return sample_token_per_row(
                step0, k_pen, temp, topk, topp, dosample
            )

        # penalty rows accept 0 and take the penalty-adjusted sampler
        # token at position 0; all-pen1 batches skip the extra sampler
        samp0 = jax.lax.cond(
            jnp.any(~pen1), penalty_sample, lambda: extra
        )
        extra = jnp.where(pen1, extra, samp0)

        pos = jnp.arange(K, dtype=jnp.int32)[None, :]
        choice = jnp.where(
            pos < n_acc[:, None], drafts,
            jnp.where(pos == n_acc[:, None], extra[:, None], greedy),
        )
        cur2 = extra
        # [B, K] target logprob of each emitted token (gather - logsumexp,
        # no [B, K, V] log-softmax materialization)
        lp_all = (
            jnp.take_along_axis(tlogits, choice[..., None], axis=-1)[..., 0]
            - jax.scipy.special.logsumexp(tlogits, axis=-1)
        )

        def lp0_penalized():
            # penalty rows sampled position 0 from the penalty-adjusted
            # distribution — report the logprob they were drawn from,
            # matching the plain path (review finding, round 5)
            step0 = apply_repetition_penalty(tlogits[:, 0], seen, penalty)
            return (jnp.take_along_axis(
                step0, choice[:, 0][:, None], axis=-1)[:, 0]
                - jax.scipy.special.logsumexp(step0, axis=-1))

        lp0 = jax.lax.cond(
            jnp.any(penalty != 1.0), lp0_penalized, lambda: lp_all[:, 0]
        )
        lp_all = lp_all.at[:, 0].set(
            jnp.where(penalty != 1.0, lp0, lp_all[:, 0])
        )

        cache = dataclasses.replace(cache, pos=cache.pos - K + n_acc + 1)
        dcache = dataclasses.replace(dcache, pos=dcache.pos - K + n_acc + 1)
        rows = jnp.arange(seen.shape[0])
        # penalty rows emit exactly cur2; spec rows don't read `seen`
        seen = seen.at[rows, cur2].set(True)
        return choice, lp_all, n_acc, cur2, cache, dcache, seen

    # ---- host API ---------------------------------------------------------

    def submit(
        self,
        prompt: list[int],
        max_new_tokens: int = 64,
        stream: Optional[queue.SimpleQueue] = None,
        do_sample: Optional[bool] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        repetition_penalty: Optional[float] = None,
        eos_token_id: Optional[int] = None,
        queue_deadline_s: Optional[float] = None,
        deadline_s: Optional[float] = None,
        adapter: Optional[str] = None,
    ) -> Request:
        if repetition_penalty is not None and repetition_penalty <= 0:
            raise ValueError(
                f"repetition_penalty must be > 0, got {repetition_penalty}"
            )
        if top_k is not None:
            # <=0 disables (the stack-wide convention); > vocab caps
            top_k = (None if top_k <= 0
                     else min(top_k, self.config.vocab_size))
        # the decode window must fit the cache alongside a minimal prompt
        # bucket; clamp instead of letting _admit derive a zero/negative
        # bucket (which would crash the engine thread)
        max_new_tokens = max(1, min(max_new_tokens, self.max_len - 16))
        req = Request(
            rid=next(self._rid), prompt=list(prompt),
            max_new_tokens=max_new_tokens, stream=stream,
            do_sample=do_sample, temperature=temperature,
            top_k=top_k, top_p=top_p,
            repetition_penalty=repetition_penalty,
            eos_token_id=eos_token_id,
            adapter=adapter,
            queue_deadline_s=(queue_deadline_s
                              if queue_deadline_s is not None
                              else self.queue_deadline_s),
            deadline_s=(deadline_s if deadline_s is not None
                        else self.deadline_s),
            submit_ts=self._clock(),
        )
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.instant("submit", ts=req.submit_ts, tid=req.rid,
                       cat="request", rid=req.rid,
                       prompt_tokens=len(req.prompt))
        if req.queue_deadline_s is not None or req.deadline_s is not None:
            self._deadlines_seen = True  # benign handler-thread race: a
            # plain bool store, read by the engine thread next step
        if not req.prompt:
            req.error = "empty prompt — nothing to generate"
            req.finish_reason = "invalid"
            req.done = True
            self._note_finish(req, req.submit_ts)
            if stream is not None:
                stream.put(None)
            return req
        bad = [t for t in req.prompt
               if not 0 <= t < self.config.vocab_size]
        if bad:
            # wrong-tokenizer ids would silently index-clip into garbage
            # generation; fail the request like the over-long case
            req.error = (
                f"prompt token id {bad[0]} outside [0, "
                f"{self.config.vocab_size}) — wrong tokenizer for this "
                "model?"
            )
            req.finish_reason = "invalid"
            req.done = True
            self._note_finish(req, req.submit_ts)
            if stream is not None:
                stream.put(None)
            return req
        if req.adapter is not None and self.adapters is None:
            # a config mistake, not overload: the caller named an
            # adapter on an engine with no registry — serving the base
            # silently would be the wrong model for that tenant
            req.error = (
                f"request names adapter {req.adapter!r} but this engine "
                "has no adapter registry (construct it with adapters=)"
            )
            req.finish_reason = "invalid"
            req.done = True
            self._note_finish(req, req.submit_ts)
            if stream is not None:
                stream.put(None)
            return req
        limit = self.max_len - max_new_tokens
        if len(req.prompt) > limit and not self.truncate_prompts:
            # FAIL FAST: admission used to tail-truncate silently, which
            # generates from a different context than the caller sent —
            # wrong output with no signal (round-5 stress finding).
            # vLLM-style rejection is the default; truncation is opt-in.
            req.error = (
                f"prompt ({len(req.prompt)} tokens) exceeds the slot "
                f"capacity ({limit} = max_len {self.max_len} - "
                f"max_new_tokens {max_new_tokens}); shorten the prompt, "
                "raise max_len, or construct the engine with "
                "truncate_prompts=True to keep the prompt tail"
            )
            req.finish_reason = "invalid"
            req.done = True
            self._note_finish(req, req.submit_ts)
            if stream is not None:
                stream.put(None)
            return req
        if self._draining:
            # graceful shutdown in progress: reject BEFORE the journal
            # append (a drained request was never accepted, and its
            # entry would resurrect it at the next start as work the
            # client already re-sent elsewhere)
            self._shed_request(req, "draining", (
                "server is draining for shutdown; retry against a "
                "fresh instance"
            ), journaled=False)
            return req
        if self.max_queue is None:
            # unbounded admission needs no check-then-put atomicity:
            # don't serialize every handler thread's submit (journal
            # append + flush included) behind one lock for a bound that
            # can never reject
            with self._stat_lock:
                self._inflight += 1
            if self._journal is not None:
                self._journal.record_submit(req)
            self._queue.put(req)
            return req
        shed_qsize = None
        with self._admission_lock:
            qsize = self._queue.qsize()
            if qsize >= self.max_queue:
                # bounded admission: overload surfaces as a fast explicit
                # rejection the client can retry, not as unbounded queue
                # latency. Checked BEFORE the journal append — a shed
                # request was never accepted, so a crash must not replay
                # it. Only the DECISION needs the lock's check-then-put
                # atomicity; the rejection itself (request-log write,
                # stream put) is blocking work that must not convoy
                # every other submit behind it (graftlint LCK102), so
                # it runs after release.
                shed_qsize = qsize
            else:
                with self._stat_lock:
                    self._inflight += 1
                if self._journal is not None:
                    self._journal.record_submit(req)
                self._queue.put(req)
        if shed_qsize is not None:
            self._shed_request(req, "queue_full", (
                f"queue full: {shed_qsize} waiting >= "
                f"max_queue {self.max_queue}; retry later"
            ), journaled=False)
        return req

    def _slot_sampling(self, req: Request) -> tuple[float, int, float, bool]:
        """Resolve a request's sampling params against engine defaults."""
        g = self.gen
        temp = req.temperature if req.temperature is not None else g.temperature
        topk = req.top_k if req.top_k is not None else (g.top_k or 0)
        topp = req.top_p if req.top_p is not None else (
            g.top_p if g.top_p is not None else 1.0
        )
        dosample = req.do_sample if req.do_sample is not None else g.do_sample
        return float(temp), int(topk or 0), float(topp), bool(dosample)

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s.req is None:
                return i
        return None

    # ---- paged page management -------------------------------------------

    def _alloc_page(self) -> Optional[int]:
        """A free page, evicting LRU radix leaves (serving/radix.py)
        while the free list is dry, then paging out holder-free
        adapters (serving/adapters.AdapterPager) — adapters share this
        pool's budget, and their host copies make page-out free to
        undo. Eviction only ever drops pages no slot holds, so it
        composes with preemption: the escalation order is free list ->
        cache eviction -> adapter page-out -> host-RAM swap-out
        (_alloc_page_preempting)."""
        if self._faults.fire("alloc_page") is not None:
            return None  # injected pool exhaustion (serving/faults.py)
        pg = self._pool.alloc()
        while pg is None and self.radix.evict_one():
            self.prefix_evictions += 1
            pg = self._pool.alloc()
        while pg is None and self._pager is not None \
                and self._pager.evict_one():
            pg = self._pool.alloc()
        return pg

    def _release_slot_pages(self, slot: int) -> None:
        for pg in self._slot_pages[slot]:
            self._pool.decref(pg)  # frees on 0; cached nodes keep theirs
        self._slot_pages[slot] = []
        self._slot_written[slot] = 0
        self._slot_pos[slot] = 0
        # retarget the idle slot's garbage decode writes at the scratch
        # page and park its position (see __init__)
        self._bt_host[slot, :] = 0
        self._bt_dirty = True
        self.cache = dataclasses.replace(
            self.cache, pos=self.cache.pos.at[slot].set(0)
        )

    def _admit_paged(self, req: Request, slot: int) -> bool:
        """Tail-truncate, reuse the longest cached prompt prefix from
        the radix tree (storage AND prefill compute, at any split
        point: full pages by descent, a mid-page divergence via the
        page-copy path), allocate fresh pages for the whole remainder,
        then prefill — monolithically, or as a chunk plan the step loop
        advances one chunk at a time (prefill_chunk_tokens). False =
        not enough pages; retry later."""
        page = self.page_size
        limit = self.max_len - req.max_new_tokens
        if len(req.prompt) > limit:
            req.prompt = req.prompt[-limit:]
        prompt = req.prompt

        # longest cached full-page run (O(prompt) incremental keys;
        # matched nodes are LRU-refreshed in O(1) each), in the
        # request's adapter namespace: pages prefilled under a LoRA
        # adapter carry its shifted K/V, so tenants never share pages
        # with each other or with the base (radix.root_for)
        path = self.radix.match(prompt, ns=req.adapter)
        shared = [nd.page for nd in path]
        n_hit = len(shared)
        lp = n_hit * page
        tail = prompt[lp:]
        head_node = path[-1] if path else self.radix.root_for(req.adapter)

        # sub-page sharing: the deepest matched node's child whose page
        # agrees with our tail for t_copy tokens lets us COPY those KV
        # slots instead of re-prefilling them. Capped at len(tail)-1 so
        # the last real token always prefills (its logits seed
        # generation).
        t_copy, src_node = 0, None
        if len(tail) > 1:
            m, child = self.radix.match_partial(head_node, tail)
            t_copy = min(m, len(tail) - 1)
            src_node = child if t_copy > 0 else None
            if src_node is None:
                t_copy = 0
        src_page = src_node.page if src_node is not None else None

        def plan(cut):
            # 16-token bucket quantum (was 32): post-hit tails are
            # short, and halving the pad floor halves the wasted
            # prefill width a mid-page split pays — this is what makes
            # sub-page reuse actually engage (the copy is skipped
            # unless it shrinks the plan)
            b = min(round_up(max(len(prompt) - lp - cut, 16), 16),
                    self.max_len - lp - cut)
            return b, -(-(lp + cut + b) // page) - n_hit

        bucket0, need0 = plan(0)
        if src_page is not None:
            bucket, need = plan(t_copy)
            # prefill cost is quantized to the bucket/page plan: a copy
            # that doesn't shrink either is pure added latency (the
            # page-copy dispatch + LRU bookkeeping) — skip it
            if bucket >= bucket0 and need >= need0:
                t_copy, src_page, src_node = 0, None, None
                bucket, need = bucket0, need0
        else:
            t_copy = 0
            bucket, need = bucket0, need0
        lp_eff = lp + t_copy
        tail2 = prompt[lp_eff:]
        if need > self.n_pages - 1:  # can NEVER be satisfied (page 0 is
            # scratch): fail now instead of head-of-line blocking forever
            self._fail_request(req, (
                f"prompt needs {need} pages but the pool only has "
                f"{self.n_pages - 1}; raise n_pages or shorten the prompt"
            ))
            return True  # consumed (failed), keep admitting others
        # incref shared pages (and the sub-page copy source) BEFORE
        # allocating fresh ones — _alloc_page's radix eviction must not
        # evict a page out of this very request's prefix (cache-only
        # holds are fair eviction game)
        for pg in shared:
            self._pool.incref(pg)
        if src_page is not None:
            self._pool.incref(src_page)
        fresh: list[int] = []
        for _ in range(need):
            pg = self._alloc_page()
            if pg is None:  # out of pages: roll back, retry next step
                for q in fresh:
                    self._pool.decref(q)
                for q in shared:
                    self._pool.decref(q)
                if src_page is not None:
                    self._pool.decref(src_page)
                return False
            fresh.append(pg)
        # admission is committed from here on (every later path prefills
        # and activates) — stamp it so queue_wait/queued exclude prefill
        self._mark_admitted(req)
        if n_hit:
            self.prefix_hits += 1

        table = shared + fresh
        self._slot_pages[slot] = table
        # page-ALIGNED coverage: _ensure_decode_pages extends in whole
        # pages, so a non-aligned start would drift the page index
        self._slot_written[slot] = len(table) * page
        row = np.zeros((self.max_pages_per_row,), np.int32)
        row[: len(table)] = table

        if src_page is not None:
            # copy the WHOLE source page (one static-shape program;
            # slots past t_copy are overwritten by the tail prefill or
            # masked by pos), then release the copy hold
            self.cache = self._copy_page(
                self.cache, jnp.asarray(src_page), jnp.asarray(fresh[0])
            )
            self._pool.decref(src_page)
            self.prefix_partial_hits += 1
            self.prefix_tokens_reused += t_copy
            self.radix.touch(src_node)  # it just proved hot

        chunk = self.prefill_chunk_tokens
        if chunk is not None and len(tail2) > chunk:
            # chunk plan: the slot is HELD (req set, active False, its
            # engine block-table row left at the scratch page) and
            # step() advances one chunk per iteration via
            # _advance_prefill — decode of the running batch proceeds
            # between chunks, so this prompt cannot stall it by more
            # than one chunk
            self._slots[slot] = _Slot(req=req, seq=next(self._seq))
            self._prefilling = _PrefillState(
                req=req, slot=slot, row=row, written=lp_eff,
                path=path, chunk=chunk,
            )
            return True

        self._bt_host[slot] = row
        self._bt_dirty = True
        self.prefill_chunks += 1
        toks = np.full((1, bucket), self.gen.pad_token_id, np.int32)
        toks[0, : len(tail2)] = tail2  # RIGHT pad: writes past pos get
        # overwritten by decode and are masked meanwhile
        logits_last, k, v, ks, vs = self._paged_prefill(
            self.model.params, self.cache.k, self.cache.v,
            self.cache.k_scale, self.cache.v_scale,
            jnp.asarray(row[None]), jnp.asarray([lp_eff], jnp.int32),
            jnp.asarray(toks), jnp.asarray(len(tail2) - 1),
            lora=self._prefill_lora(req),
        )
        self.cache = dataclasses.replace(
            self.cache, k=k, v=v, k_scale=ks, v_scale=vs,
            pos=self.cache.pos.at[slot].set(len(prompt)),
            start=self.cache.start.at[slot].set(0),
        )
        self._slot_pos[slot] = len(prompt)

        self._register_prefix(prompt, path, table, ns=req.adapter)

        if self.speculative:
            # prefix-cache hits only save TARGET prefill; the draft
            # always prefills its full context into the dense draft pool
            self._admit_draft(slot, prompt, limit)

        self._activate(slot, req, logits_last[None])
        return True

    def _register_prefix(self, prompt: list[int], path: list,
                         table: list[int], ns=None) -> None:
        """Register the prompt's fully-covered pages past the matched
        run as radix nodes (the cache takes its own page reference).
        An existing edge keeps its canonical page — our duplicate stays
        slot-only and frees at release. `ns` = the request's adapter
        name: adapter-prefilled pages register under that tenant's own
        radix root, never the shared base tree."""
        page = self.page_size
        node = path[-1] if path else self.radix.root_for(ns)
        for i in range(len(path), len(prompt) // page):
            key = tuple(prompt[i * page: (i + 1) * page])
            nxt = node.children.get(key)
            if nxt is None:
                nxt = self.radix.insert(node, key, table[i])
            node = nxt

    def _advance_prefill(self) -> None:
        """Run AT MOST ONE chunk of the at-most-one in-flight chunked
        prefill: the per-step decode stall a new prompt can inflict is
        bounded by one chunk. The final chunk installs the real block
        table, registers radix nodes, and activates the slot (first
        token emits — TTFT closes here)."""
        st = self._prefilling
        if st is None:
            return
        prompt = st.req.prompt
        rem = len(prompt) - st.written
        n = min(st.chunk, rem)
        last = n == rem
        bucket = min(round_up(max(n, 16), 16), self.max_len - st.written)
        toks = np.full((1, bucket), self.gen.pad_token_id, np.int32)
        toks[0, :n] = prompt[st.written: st.written + n]
        self.prefill_chunks += 1
        logits_last, k, v, ks, vs = self._paged_prefill(
            self.model.params, self.cache.k, self.cache.v,
            self.cache.k_scale, self.cache.v_scale,
            jnp.asarray(st.row[None]), jnp.asarray([st.written], jnp.int32),
            jnp.asarray(toks), jnp.asarray(n - 1),
            lora=self._prefill_lora(st.req),
        )
        self.cache = dataclasses.replace(
            self.cache, k=k, v=v, k_scale=ks, v_scale=vs,
        )
        st.written += n
        if not last:
            return
        slot = st.slot
        self._prefilling = None
        self._bt_host[slot] = st.row
        self._bt_dirty = True
        self.cache = dataclasses.replace(
            self.cache,
            pos=self.cache.pos.at[slot].set(len(prompt)),
            start=self.cache.start.at[slot].set(0),
        )
        self._slot_pos[slot] = len(prompt)
        self._register_prefix(prompt, st.path, self._slot_pages[slot],
                              ns=st.req.adapter)
        self._activate(slot, st.req, logits_last[None])

    def _admit_draft(self, slot: int, prompt: list[int], limit: int) -> None:
        """Left-pad-prefill the speculative draft pool's row for a newly
        admitted request — one definition shared by the dense and paged
        admission paths so their draft discipline can never drift."""
        bucket = min(round_up(max(len(prompt), 16), 64), limit)
        dprompt = prompt[-bucket:]
        tokens = np.full((1, bucket), self.gen.pad_token_id, np.int32)
        tokens[0, bucket - len(dprompt):] = dprompt
        pad = bucket - len(dprompt)
        _, dpcache = self._prefill(
            self._draft_params, jnp.asarray(tokens),
            jnp.asarray([pad], jnp.int32), bucket=bucket,
        )
        self.dcache = self._insert(
            self.dcache, dpcache, jnp.asarray(slot), jnp.asarray(pad)
        )

    def _ensure_decode_pages(self, need_tokens: int = 1) -> None:
        """Before a decode step, every active slot whose next `need_tokens`
        writes would run past its allocation gets more pages (speculative
        verify writes draft_k tokens before rolling back — the pages must
        exist or the scatter clamps into a neighbour page). A slot that
        cannot extend because the POOL is dry preempts a victim to host
        RAM (youngest-first) instead of silently truncating its output;
        'length' remains only for true logical capacity (max_pages_per_row)
        or a pool that provably cannot support the request at all."""
        for i in np.nonzero(self.active)[0]:
            slot = int(i)
            while (self.active[slot]
                   and self._slot_pos[slot] + need_tokens
                   > self._slot_written[slot]):
                idx = len(self._slot_pages[slot])
                if idx >= self.max_pages_per_row:  # logical capacity hit
                    self._finish(slot, "length")
                    break
                pg = self._alloc_page_preempting(slot)
                if pg is None:
                    if self.active[slot]:  # not self-preempted: stuck
                        self._finish(slot, "length")
                    break
                self._slot_pages[slot].append(pg)
                self._slot_written[slot] += self.page_size
                self._bt_host[slot, idx] = pg
                self._bt_dirty = True

    # ---- preemption (host-RAM KV swap) ------------------------------------

    def _alloc_page_preempting(self, slot: int) -> Optional[int]:
        """_alloc_page, escalating to preemption under pool pressure:
        swap victims out (policy order) until a page frees. With no other
        victim, the requesting slot preempts ITSELF — but only if it has
        made progress since its last resume; a no-progress self-preempt
        proves the pool cannot support the request (swap-in would need
        the very pages that are missing) and would livelock."""
        while True:
            pg = self._alloc_page()
            if pg is not None or not self.preemption:
                return pg
            victim = self._pick_victim(exclude=slot)
            if victim is not None:
                self._preempt_slot(victim)
                continue
            if self._abort_prefill_for_pages():
                continue  # the chunk plan yielded its pages
            s = self._slots[slot]
            if s.resumed_pos < 0 or self._slot_pos[slot] > s.resumed_pos:
                self._preempt_slot(slot)  # caller sees the slot inactive
            return None

    def _abort_prefill_for_pages(self) -> bool:
        """Yield a mid-chunked-prefill plan's pages to allocation
        pressure: a decoding stream must not be truncated (nor a
        parked request failed) while an inactive chunk plan sits on
        the very pages it needs. The plan has no decode state yet, so
        'preempting' it is simply releasing its slot and putting the
        request back at the queue's FRONT (it was the most recent pop
        — FIFO order is preserved); prefill restarts later from
        whatever the cache still covers, and output is unaffected
        because nothing was emitted. The re-wait is not re-counted in
        queue_wait (admit_ts stays from the first admission)."""
        st = self._prefilling
        if st is None:
            return False
        self._free_slot_state(st.slot)  # releases pages + clears plan
        with self._queue.mutex:  # raw deque surgery, _sweep_queue style
            self._queue.queue.appendleft(st.req)
        return True

    def _pick_victim(self, exclude: int) -> Optional[int]:
        """Victim slot per policy. youngest = most recently (re)admitted:
        it loses the least progress and, being FIFO-resumed behind older
        preempted work, cannot starve the oldest request — the oldest is
        never chosen while anyone else is active, so it always completes
        and frees its pages."""
        cands = [(s.seq, i) for i, s in enumerate(self._slots)
                 if s.req is not None and i != exclude
                 and self.active[i]]  # a mid-chunked-prefill slot has
        # no resumable decode state to swap; it is never a victim
        if not cands:
            return None
        pick = max(cands) if self.preemption_policy == "youngest" \
            else min(cands)
        return pick[1]

    def _preempt_slot(self, slot: int) -> None:
        """Swap a slot's KV to host RAM and requeue its request with the
        tokens generated so far; the slot frees WITHOUT finishing the
        request (its stream sees a pause, never a sentinel). Decode after
        the matching swap-in is bit-exact: the blob preserves the cache
        bytes and the resume restores cur/seen/sampling state untouched."""
        s = self._slots[slot]
        req = s.req
        now = self._clock()
        self._flush_decode_window(slot, now)
        if self.paged:
            pos = self._slot_pos[slot]
            n_keep = -(-pos // self.page_size)  # pages holding real KV
            from bigdl_tpu import kvpaged

            blob = kvpaged.swap_out_pages(
                self.cache, self._slot_pages[slot][:n_keep]
            )
            start = 0
        else:
            pos = int(np.asarray(self.cache.pos[slot]))
            start = int(np.asarray(self.cache.start[slot]))
            # only the live region [0, pos) travels; bucketing to 64
            # bounds the distinct swap-in program shapes (mirrors the
            # paged twin's one-program-per-page-count)
            n = min(round_up(max(pos, 1), 64), self.cache.max_len)
            blob = kvcache.swap_out_row(self.cache, slot, n)
            n_keep = 0
        entry = _Preempted(
            req=req, cur=int(np.asarray(self.cur[slot])),
            remaining=s.remaining, eos=s.eos, pos=pos, start=start,
            seq=s.seq, temp=float(self._temp[slot]),
            topk=int(self._topk[slot]), topp=float(self._topp[slot]),
            dosample=bool(self._dosample[slot]),
            penalty=float(self._penalty[slot]),
            seen=np.asarray(self.seen[slot]), blob=blob, n_pages=n_keep,
        )
        req.preemptions += 1
        self.preemptions += 1
        req.preempt_ts = now  # the "preempted" span + resume_wait
        # histogram close on this stamp at swap-in
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.instant("swap_out", ts=now, tid=req.rid, cat="request",
                       rid=req.rid, pos=pos, pages=n_keep)
        self._preempted.append(entry)
        # free the slot WITHOUT _finish: the request is alive, just parked
        self._free_slot_state(slot)
        if not self.paged:
            self.cache = dataclasses.replace(
                self.cache, pos=self.cache.pos.at[slot].set(0)
            )

    def _resume_preempted(self, entry: _Preempted, slot: int) -> bool:
        """Swap a parked request back into `slot` (fresh pages / any free
        row — physical placement is irrelevant, the block table / row
        index re-maps it). False = the pool cannot hold the restore yet;
        the entry stays queued and newer admissions wait behind it."""
        req = entry.req
        if self.paged:
            fresh: list[int] = []
            for _ in range(entry.n_pages):
                pg = self._alloc_page()
                if pg is None:  # roll back; retry when pages free up
                    for q in fresh:
                        self._pool.decref(q)
                    return False
                fresh.append(pg)
            self._slot_pages[slot] = fresh
            self._slot_written[slot] = entry.n_pages * self.page_size
            row = np.zeros((self.max_pages_per_row,), np.int32)
            row[: entry.n_pages] = fresh
            self._bt_host[slot] = row
            self._bt_dirty = True
            b = entry.blob
            self.cache = self._swap_in(
                self.cache, b.k, b.v, b.k_scale, b.v_scale,
                jnp.asarray(fresh, jnp.int32),
            )
            self.cache = dataclasses.replace(
                self.cache,
                pos=self.cache.pos.at[slot].set(entry.pos),
                start=self.cache.start.at[slot].set(0),
            )
            self._slot_pos[slot] = entry.pos
        else:
            k, v, ks, vs = entry.blob
            self.cache = self._dense_swap_in(
                self.cache, k, v, ks, vs, jnp.asarray(slot),
                jnp.asarray(entry.pos, jnp.int32),
                jnp.asarray(entry.start, jnp.int32),
            )
        self.cur = self.cur.at[slot].set(entry.cur)
        self.seen = self.seen.at[slot].set(jnp.asarray(entry.seen))
        self._temp[slot], self._topk[slot] = entry.temp, entry.topk
        self._topp[slot], self._dosample[slot] = entry.topp, entry.dosample
        self._penalty[slot] = entry.penalty
        self._slots[slot] = _Slot(
            req=req, remaining=entry.remaining, eos=entry.eos,
            seq=entry.seq, resumed_pos=entry.pos,
        )
        # the parked request kept its adapter reference (host-RAM
        # residency survived the swap); re-point the slot at it
        self._set_slot_adapter(slot, req)
        self.active[slot] = True
        if self.speculative:
            # the draft pool was not swapped (drafts are advisory — any
            # draft content yields the same emitted tokens); rebuild the
            # row from the full context so acceptance rates stay healthy
            self._admit_draft(slot, req.prompt + req.out_tokens,
                              self.max_len - req.max_new_tokens)
        now = self._clock()
        if req.preempt_ts is not None:
            parked = max(now - req.preempt_ts, 0.0)
            # satellite (ISSUE 11): the requeue wait of a preempted-and-
            # resumed request is its own histogram — it was previously
            # invisible (admit_ts is already set, so queue_wait never
            # fires again for a resume)
            self.resume_wait.observe(parked)
            req.preempted_s += parked
            tr = self.tracer
            if tr is not None and tr.enabled:
                tr.complete("preempted", req.preempt_ts, parked,
                            tid=req.rid, cat="request", rid=req.rid,
                            pages=entry.n_pages)
            req.preempt_ts = None
        if req.last_token_ts is not None:
            # rebase the inter-token clock past the parked stretch: the
            # stall is accounted in resume_wait_seconds, and the next
            # decode window must open after the "preempted" span closes
            req.last_token_ts = now
        self.preemption_resumes += 1
        return True

    def preempt(self, req: Request) -> None:
        """Thread-safe operator/server-initiated preemption: park the
        request's KV in host RAM at the engine thread's next step and
        requeue it for resume. Works for dense and paged pools. Only a
        request currently DECODING in a slot is acted on — one that is
        still queued, already parked, or finished has no device KV to
        swap, so the call is a no-op for it (the marker is dropped at the
        next step rather than lingering to ambush a later admission)."""
        if self._family_cache is not None:
            raise NotImplementedError(
                f"preemption is not wired for "
                f"{self.config.model_type}'s family cache"
            )
        self._preempt_requested.add(req.rid)

    def _reap_preempt_requests(self) -> None:
        if not self._preempt_requested:
            return
        # swap-then-clear: rids that don't match a live slot are dropped,
        # not kept — handler threads may add() concurrently and those
        # land in the fresh set for the next step
        pending, self._preempt_requested = self._preempt_requested, set()
        for i, s in enumerate(self._slots):
            if (s.req is not None and s.req.rid in pending
                    and self.active[i]):
                # mid-chunked-prefill slots are skipped like queued
                # requests: no decode state exists to park yet (the
                # marker drops; re-request once decoding)
                self._preempt_slot(i)

    # ---- multi-tenant LoRA adapters (serving/adapters.py; §7) -------------

    def _resolve_adapter(self, req: Request) -> bool:
        """Acquire the request's named adapter at admission: load/verify
        through the registry (LRU-refreshing it) and take the request's
        ONE reference — held across preemption parking and the paged
        OOM-retry wait, released at the terminal finish. False = the
        adapter is missing/corrupt/mismatched: the request finishes
        "error" with the structured message and the caller admits the
        next one (a bad tenant artifact must never fail_all a batch)."""
        from bigdl_tpu.serving.adapters import AdapterError

        if req.rid in self._adapter_refs:  # OOM-retry / prefill-abort
            # re-admission: the reference is already held; re-page-in
            # best-effort (the pages may have been evicted while the
            # request was parked — a dry pool just means the gather
            # falls back to the registry's host copy)
            if self._pager is not None:
                try:
                    self._pager.ensure(self._adapter_refs[req.rid],
                                       req.rid)
                except AdapterError:
                    pass
            return True
        try:
            entry = self.adapters.acquire(req.adapter)
        except AdapterError as e:
            self._fail_request(req, str(e))
            return False
        try:
            self._check_adapter_dims(entry)
        except AdapterError as e:
            # wrong-base artifact: count it as a load failure and drop
            # it from residency (reject) — a resident entry every
            # request errors on would read as a healthy registry in
            # /metrics while squatting on budget
            self.adapters.reject(entry)
            self._fail_request(req, str(e))
            return False
        self._adapter_refs[req.rid] = entry
        if self._pager is not None:
            try:
                self._pager.ensure(entry, req.rid)
            except AdapterError as e:
                # injected page-in stall (serving/faults.py): quarantine
                # exactly this request — release the reference we just
                # took so the registry's refcounts stay exact
                del self._adapter_refs[req.rid]
                self.adapters.release(entry)
                self._fail_request(req, str(e))
                return False
            # ensure() returning False (pool dry even after eviction) is
            # NOT an error: the gather reads the host copy instead —
            # adapter paging never preempts KV to make room
        return True

    def _check_adapter_dims(self, entry) -> None:
        """An adapter trained against a different base would scatter
        garbage through the epilogue einsum (or fail deep inside XLA);
        fail it structurally at admission instead."""
        from bigdl_tpu.serving.adapters import AdapterError
        from bigdl_tpu.train.qlora import _target_dims

        L = self.config.num_hidden_layers
        for t in entry.targets:
            try:
                out_d, in_d = _target_dims(self.config, t)
            except KeyError:
                raise AdapterError(
                    entry.name, "rank_mismatch",
                    f"unknown lora target {t!r} for this model family",
                ) from None
            a = entry.layers[t]["a"]
            b = entry.layers[t]["b"]
            if (tuple(a.shape) != (L, entry.rank, in_d)
                    or tuple(b.shape) != (L, out_d, entry.rank)):
                raise AdapterError(
                    entry.name, "rank_mismatch",
                    f"target {t}: a{tuple(a.shape)} / b{tuple(b.shape)} "
                    f"do not fit this model's [L={L}, r={entry.rank}, "
                    f"in={in_d}] / [L, out={out_d}, r] — adapter trained "
                    "on a different base?",
                )

    def _set_slot_adapter(self, slot: int, req: Request) -> None:
        """Point the slot at the request's (possibly absent) adapter
        entry and invalidate the batched decode tree only when the
        assignment actually changed."""
        if self.adapters is None:
            return
        entry = self._adapter_refs.get(req.rid)
        if self._slot_adapter[slot] is not entry:
            self._slot_adapter[slot] = entry
            self._blora_dirty = True

    def _prefill_lora(self, req: Request):
        """The request's single-row rank-bucketed adapter tree for the
        prefill kernels (None = base). Also stamps _last_prefill_rank /
        _last_prefill_targets for the sim's cost wrappers."""
        entry = self._adapter_refs.get(req.rid)
        self._last_prefill_rank = entry.rank if entry is not None else 0
        self._last_prefill_targets = (entry.targets if entry is not None
                                      else ())
        if entry is None:
            return None
        return entry.tree()

    def _gather_blora(self) -> Optional[dict]:
        """The decode step's batched adapter tree: per target,
        [L, B, rb, in] A-stacks and [L, B, out, rb] B-stacks over every
        slot (zero rows + scale 0 for adapter-less slots), rb = the
        power-of-two bucket of the max rank in the batch
        (adapters.rank_bucket) — compile variants are bounded by
        (target-set, bucket), never by which tenants happen to share a
        step. Rebuilt only when the slot->adapter assignment changes;
        None when no active slot carries an adapter (the base-only
        program keeps serving)."""
        if self.adapters is None:
            return None
        if not self._blora_dirty:
            return self._blora
        self._blora_dirty = False
        entries = self._slot_adapter
        live = [e for e in entries if e is not None]
        if not live:
            self._blora = None
            return None
        from bigdl_tpu.serving.adapters import rank_bucket

        B = self.n_slots
        L = self.config.num_hidden_layers
        rb = rank_bucket(max(e.rank for e in live))
        targets = sorted({t for e in live for t in e.targets})
        # unified paging: adapters resident in the shared page pool are
        # read straight out of their device pages — the host->device
        # transfer below shrinks to only the non-resident stragglers
        # (dry-pool fallbacks). Device reads round through the same
        # bf16 the host path casts to, so the two sources are
        # bit-identical in the epilogue.
        dev: dict = {}
        if self._pager is not None:
            for e in live:
                if e.name not in dev:
                    lv = self._pager.leaves(e.name)
                    if lv is not None:
                        dev[e.name] = lv
        layers: dict = {}
        for t in targets:
            ref = next(e.layers[t] for e in live if t in e.layers)
            in_d = int(np.asarray(ref["a"]).shape[-1])
            out_d = int(np.asarray(ref["b"]).shape[-2])
            a = np.zeros((L, B, rb, in_d), np.float32)
            b = np.zeros((L, B, out_d, rb), np.float32)
            for i, e in enumerate(entries):
                if e is None or t not in e.layers or e.name in dev:
                    continue
                a[:, i, : e.rank, :] = np.asarray(
                    e.layers[t]["a"], np.float32
                )
                b[:, i, :, : e.rank] = np.asarray(
                    e.layers[t]["b"], np.float32
                )
            ja = jnp.asarray(a, jnp.bfloat16)
            jb = jnp.asarray(b, jnp.bfloat16)
            for i, e in enumerate(entries):
                if e is None or t not in e.layers or e.name not in dev:
                    continue
                lv = dev[e.name][t]
                ja = ja.at[:, i, : e.rank, :].set(lv["a"])
                jb = jb.at[:, i, :, : e.rank].set(lv["b"])
            layers[t] = {"a": ja, "b": jb}
        scale = np.zeros((B,), np.float32)
        for i, e in enumerate(entries):
            if e is not None:
                scale[i] = e.scale
        self._blora = {"layers": layers, "scale": jnp.asarray(scale)}
        return self._blora

    # ---- admission --------------------------------------------------------

    # cache-aware admission: oldest entries scored per pop (bounds the
    # under-mutex radix probe; see _pop_deepest_match)
    _ADMIT_SCAN_WINDOW = 64

    def _pop_request(self) -> Optional[Request]:
        if self._waiting is not None:
            req, self._waiting = self._waiting, None
            return req
        if self.paged:
            return self._pop_deepest_match()
        try:
            return self._queue.get_nowait()
        except queue.Empty:
            return None

    def _pop_deepest_match(self) -> Optional[Request]:
        """Cache-aware admission ordering (docs/serving.md §6): among
        the queued admissible requests, admit the one with the DEEPEST
        radix prefix match first — it frees the most prefill work and
        touches its cached pages before eviction pressure can drop
        them. Strict-greater comparison keeps ties (including the
        all-miss common case) in FIFO order, so a workload with no
        shared prefixes schedules exactly as before; queue/request
        deadlines still bound how long a 0-match request can be
        out-prioritized. Probe is read-only (radix.match_len): scoring
        must not LRU-promote pages for requests that stay queued.

        The scan holds the queue mutex (raw deque surgery, _sweep_queue
        style), so it is BOUNDED: only the oldest _ADMIT_SCAN_WINDOW
        entries are scored — an unbounded queue under overload must not
        turn every admission into an O(queue x prompt) stall that also
        blocks handler-thread submits for the scan's duration."""
        with self._queue.mutex:
            q = self._queue.queue
            if not q:
                return None
            if len(q) > 1 and self.radix.n_nodes:
                n = min(len(q), self._ADMIT_SCAN_WINDOW)
                best_i, best_d = 0, self.radix.match_len(
                    q[0].prompt, ns=q[0].adapter)
                for i in range(1, n):
                    d = self.radix.match_len(q[i].prompt, ns=q[i].adapter)
                    if d > best_d:
                        best_i, best_d = i, d
                if best_i:
                    req = q[best_i]
                    del q[best_i]
                    return req
            return q.popleft()

    def _shed_request(self, req: Request, kind: str, msg: str,
                      journaled: bool = True) -> None:
        """Overload rejection: explicit, fast, retryable (the API server
        maps kind "queue_full" to 429 and "queue_deadline" to 503, both
        with Retry-After)."""
        req.shed_kind = kind
        self._finish_detached(req, "shed", error=msg, journaled=journaled)
        self._bump("requests_shed")

    def _finish_detached(self, req: Request, reason: str,
                         error: Optional[str] = None,
                         journaled: bool = True) -> None:
        """Terminal state for a request NOT currently in a slot (queued /
        parked): mirrors _finish's journal + stream discipline.
        journaled=False is for requests that were never accepted (shed at
        submit) — they have no journal entry to tombstone and no
        in-flight charge to release."""
        if journaled:
            with self._stat_lock:
                self._inflight -= 1
        if error is not None:
            req.error = error
        req.finish_reason = reason
        req.done = True
        self._note_finish(req, self._clock())
        if journaled and self._journal is not None:
            self._journal.record_done(req.rid)
        if req.stream is not None:
            req.stream.put(None)

    def _note_finish(self, req: Request, now: float) -> None:
        """Terminal-state accounting shared by every finish path (slot,
        detached, submit-time rejection): per-reason counter, trace
        events, and the derived-timings request-log record. Handler
        threads reach this via shed/invalid, hence the lock on the
        counter dict."""
        reason = req.finish_reason or "?"
        with self._stat_lock:
            self.finish_reasons[reason] += 1
        entry = self._adapter_refs.pop(req.rid, None)
        if entry is not None:
            # the request's one adapter hold releases exactly at its
            # terminal state (every finish path funnels through here);
            # a refcount-0 adapter becomes fair eviction game
            self.adapters.release(entry)
            if self._pager is not None:
                # the device pages mirror the hold: holder-free pages
                # become page-out candidates for _alloc_page
                self._pager.drop_holder(req.rid)
        tr = self.tracer
        if req.preempt_ts is not None:
            # died while PARKED (deadline/cancel/fail_all before any
            # resume): close the preempted stretch here or the record
            # reports preempted_s=0 for a request that spent its whole
            # life in host RAM, and the trace dangles a swap_out with
            # no span. Engine-thread only: handler threads reach
            # _note_finish solely for never-admitted requests.
            parked = max(now - req.preempt_ts, 0.0)
            req.preempted_s += parked
            if tr is not None and tr.enabled:
                tr.complete("preempted", req.preempt_ts, parked,
                            tid=req.rid, cat="request", rid=req.rid,
                            outcome=reason)
            req.preempt_ts = None
        if tr is not None and tr.enabled:
            if req.admit_ts is None and reason != "invalid":
                # died waiting (shed / queue timeout / cancelled while
                # queued): close its queued span so the wait is visible
                tr.complete("queued", req.submit_ts,
                            now - req.submit_ts, tid=req.rid,
                            cat="request", rid=req.rid, outcome=reason)
            args = {"rid": req.rid, "finish_reason": reason,
                    "tokens": len(req.out_tokens)}
            if req.first_token_ts is not None:
                args["ttft_s"] = round(
                    req.first_token_ts - req.submit_ts, 6)
            if req.admit_ts is not None:
                args["queue_wait_s"] = round(
                    req.admit_ts - req.submit_ts, 6)
            if req.preempted_s:
                args["preempted_s"] = round(req.preempted_s, 6)
            tr.instant("finish", ts=now, tid=req.rid, cat="request",
                       **args)
        if self._request_log is not None:
            self._request_log.write(self._request_record(req, now))

    def _request_record(self, req: Request, now: float) -> dict:
        """The structured per-request JSONL record: every timing the
        TTFT/ITL/queue-wait dashboards derive, attached to one rid."""
        rec = {
            "ts": round(now, 6), "rid": req.rid,
            "finish_reason": req.finish_reason,
            "prompt_tokens": len(req.prompt),
            "output_tokens": len(req.out_tokens),
        }
        if req.admit_ts is not None:
            rec["queue_wait_s"] = round(req.admit_ts - req.submit_ts, 6)
        if req.first_token_ts is not None:
            rec["ttft_s"] = round(req.first_token_ts - req.submit_ts, 6)
            n = len(req.out_tokens)
            if n > 1 and req.last_token_ts is not None:
                # time-per-output-token over the decode stretch. Parked
                # time is SUBTRACTED (it is reported separately below) —
                # first->last spans any host-RAM stretch even though the
                # ITL clock rebases at resume
                decoding = max(req.last_token_ts - req.first_token_ts
                               - req.preempted_s, 0.0)
                rec["tpot_s"] = round(decoding / (n - 1), 6)
        if req.preemptions:
            rec["preemptions"] = req.preemptions
            rec["preempted_s"] = round(req.preempted_s, 6)
        if req.shed_kind is not None:
            rec["shed_kind"] = req.shed_kind
        if req.error:
            rec["error"] = req.error
        return rec

    @staticmethod
    def _expired(req: Request, now: float) -> Optional[str]:
        """The deadline a request has blown, if any."""
        if (req.deadline_s is not None
                and now - req.submit_ts > req.deadline_s):
            return "deadline_s"
        if (req.admit_ts is None and req.queue_deadline_s is not None
                and now - req.submit_ts > req.queue_deadline_s):
            return "queue_deadline_s"
        return None

    def _mark_admitted(self, req: Request) -> None:
        """Stamp the request's (first) admission: the moment it left the
        queue and prefill work began. queue_wait therefore measures pure
        waiting — prefill time is its own phase (prefill_seconds and the
        "prefill" span) — and the "queued" span ends exactly where the
        prefill span starts."""
        if req.admit_ts is not None:
            return
        req.admit_ts = self._clock()
        self.queue_wait.observe(req.admit_ts - req.submit_ts)
        tr = self.tracer
        if tr is not None and tr.enabled:
            tr.complete("queued", req.submit_ts,
                        req.admit_ts - req.submit_ts, tid=req.rid,
                        cat="request", rid=req.rid)

    def _activate(self, slot: int, req: Request, logits_last) -> None:
        """Shared post-prefill bookkeeping: sample the first token, arm
        the slot's sampling params, emit."""
        temp, topk, topp, dosample = self._slot_sampling(req)
        penalty = (req.repetition_penalty
                   if req.repetition_penalty is not None
                   else self.gen.repetition_penalty)
        if penalty != 1.0:
            from bigdl_tpu.generate import apply_repetition_penalty, \
                seen_from_prompt

            prompt_arr = np.asarray([req.prompt], np.int32)
            row = seen_from_prompt(
                jnp.asarray(prompt_arr), jnp.zeros((1,), jnp.int32),
                self.config.vocab_size,
            )[0]
            logits_last = apply_repetition_penalty(
                logits_last, row[None], jnp.asarray(penalty, jnp.float32)
            )
        else:
            row = jnp.zeros((self.config.vocab_size,), jnp.bool_)
        self._rng, k = jax.random.split(self._rng)
        first = int(sample_token_per_row(
            logits_last, k,
            jnp.asarray([temp], jnp.float32),
            jnp.asarray([topk], jnp.int32),
            jnp.asarray([topp], jnp.float32),
            jnp.asarray([dosample], jnp.bool_),
        )[0])
        self.cur = self.cur.at[slot].set(first)
        eos = (req.eos_token_id if req.eos_token_id is not None
               else self.gen.eos_token_id)
        self._slots[slot] = _Slot(
            req=req, remaining=req.max_new_tokens - 1, eos=eos,
            seq=next(self._seq),
        )
        self._temp[slot], self._topk[slot] = temp, topk
        self._topp[slot], self._dosample[slot] = topp, dosample
        self._penalty[slot] = penalty
        self.seen = self.seen.at[slot].set(row).at[slot, first].set(True)
        self._set_slot_adapter(slot, req)
        self.active[slot] = True
        row_lp = jax.nn.log_softmax(
            jnp.asarray(logits_last, jnp.float32).reshape(-1)
        )
        first_lp = float(row_lp[first])
        first_top = None
        if self.logprobs_top_k:
            tv, ti = jax.lax.top_k(row_lp, self.logprobs_top_k)
            first_top = {int(t): float(l)
                         for t, l in zip(np.asarray(ti), np.asarray(tv))}
        # prefill phase closes HERE (the first-token sample above was a
        # host sync, so the span covers real work), strictly before the
        # first emit — the request track stays monotonically nested:
        # queued | prefill | decode windows ...
        now = self._clock()
        if req.admit_ts is not None:
            self.prefill_seconds.observe(now - req.admit_ts)
            tr = self.tracer
            if tr is not None and tr.enabled:
                tr.complete("prefill", req.admit_ts, now - req.admit_ts,
                            tid=req.rid, cat="request", rid=req.rid,
                            prompt_tokens=len(req.prompt))
        self._emit(slot, first, first_lp, first_top)

    def _admit_dense(self, req: Request, slot: int) -> None:
        self._mark_admitted(req)
        # decode writes land at [bucket, bucket + max_new_tokens): keep
        # that window inside the cache, tail-truncating over-long prompts
        limit = self.max_len - req.max_new_tokens
        bucket = min(round_up(max(len(req.prompt), 16), 64), limit)
        if len(req.prompt) > bucket:
            req.prompt = req.prompt[-bucket:]
        tokens = np.full((1, bucket), self.gen.pad_token_id, np.int32)
        tokens[0, bucket - len(req.prompt):] = req.prompt
        pad = bucket - len(req.prompt)
        self.prefill_chunks += 1  # a monolithic prefill is one chunk
        logits_last, pcache = self._prefill(
            self.model.params, jnp.asarray(tokens),
            jnp.asarray([pad], jnp.int32), bucket=bucket,
            lora=self._prefill_lora(req),
        )
        self.cache = self._insert(
            self.cache, pcache, jnp.asarray(slot), jnp.asarray(pad)
        )
        if self.speculative:
            self._admit_draft(slot, req.prompt, limit)
        self._activate(slot, req, logits_last)

    def _admit(self) -> None:
        while True:
            slot = self._free_slot()
            if slot is None:
                return
            # preempted requests resume FIRST, in preemption order: they
            # are the oldest in-flight work, and admitting new requests
            # past a blocked resume would starve it of the very pages it
            # waits for
            if self._preempted:
                # dead entries (cancelled / expired) at ANY depth were
                # already dropped by _sweep_preempted this step
                entry = self._preempted[0]
                req = entry.req
                if self._resume_preempted(entry, slot):
                    self._preempted.popleft()
                    continue
                if not self.active.any() and self._prefilling is None:
                    # nothing left to free pages: the pool cannot hold
                    # the restore, ever — fail instead of hanging. A
                    # live chunk plan is future page supply (its slot
                    # activates, decodes, and frees), so the resume
                    # waits it out rather than failing spuriously.
                    self._preempted.popleft()
                    self._fail_request(req, (
                        f"cannot resume preempted request: restoring "
                        f"{entry.n_pages} pages exceeds the free pool; "
                        "raise n_pages"
                    ))
                    continue
                return  # wait for pages before admitting anything newer
            if self._prefilling is not None:
                # at most ONE request prefills at a time: admitting
                # another would either stack a second monolithic
                # prefill into this step (the stall chunking bounds) or
                # need a second chunk plan — queued work waits the few
                # steps until the current plan lands
                return
            req = self._pop_request()
            if req is None:
                return
            if req.rid in self._cancelled:  # cancelled while queued: a
                # timed-out/disconnected client must not burn the slot
                self._cancelled.pop(req.rid, None)
                self._finish_detached(req, "stop")
                continue
            now = self._clock()
            which = self._expired(req, now)
            if which is not None:
                self._expire_queued(req, which, now)
                continue
            if req.adapter is not None and not self._resolve_adapter(req):
                continue  # structured failure: ONE request errors, the
                # batch keeps serving (never fail_all for a bad adapter)
            if self.paged:
                if not self._admit_paged(req, slot):
                    self._waiting = req  # pool full: retry after frees
                    return
            else:
                self._admit_dense(req, slot)

    def _emit(self, slot: int, token: int,
              logprob: Optional[float] = None,
              top_logprobs: Optional[dict] = None) -> None:
        s = self._slots[slot]
        eos = s.eos
        if eos is not None and token == eos:
            # the EOS id terminates the stream but is not generated text
            self._finish(slot, "stop")
            return
        req = s.req
        now = self._clock()
        prev = req.last_token_ts
        if req.first_token_ts is None:
            req.first_token_ts = now
            self.ttft.observe(now - req.submit_ts)
            prev = now
        else:
            # wall-clock gap between consecutive emits as a streaming
            # client sees them (a speculative burst yields ~0 gaps —
            # that IS the client experience). Parked time is excluded:
            # resume rebases last_token_ts, and the stall is accounted
            # in resume_wait_seconds instead.
            self.itl.observe(now - prev)
        req.last_token_ts = now
        tr = self.tracer
        if tr is not None and tr.enabled:
            # coalesce decode into one span per trace_decode_every
            # tokens; each window opens where the previous span closed,
            # keeping the request track monotonically nested
            if s.n_win == 0:
                s.t_win = prev
            s.n_win += 1
            if s.n_win >= self.trace_decode_every:
                tr.complete("decode", s.t_win, now - s.t_win,
                            tid=req.rid, cat="request", rid=req.rid,
                            tokens=s.n_win)
                s.n_win = 0
        s.req.out_tokens.append(token)
        if logprob is not None:
            s.req.out_logprobs.append(logprob)
        if top_logprobs is not None:
            s.req.out_top_logprobs.append(top_logprobs)
        if s.req.stream is not None:
            s.req.stream.put(token)
        if s.remaining <= 0:
            self._finish(slot, "length")

    def _flush_decode_window(self, slot: int, now: float) -> None:
        """Emit the slot's partial decode-window span (finish/preempt
        must not drop the tail tokens' span)."""
        s = self._slots[slot]
        tr = self.tracer
        if (tr is not None and tr.enabled and s.n_win > 0
                and s.req is not None):
            tr.complete("decode", s.t_win, now - s.t_win, tid=s.req.rid,
                        cat="request", rid=s.req.rid, tokens=s.n_win)
        s.n_win = 0

    def _finish(self, slot: int, reason: str = "stop",
                counted: bool = True) -> None:
        s = self._slots[slot]
        now = self._clock()
        self._flush_decode_window(slot, now)
        s.req.finish_reason = reason
        s.req.done = True
        # before the injected crash point: a crash inside _finish leaves
        # the request terminal (fail_all preserves it), so its in-flight
        # charge must already be released. Same for the finish
        # accounting below: the request IS terminal either way, and a
        # replayed request counts again in the successor process (the
        # request log is at-least-once across the crash window, like
        # the journal).
        with self._stat_lock:
            self._inflight -= 1
        self._note_finish(s.req, now)
        if counted and reason in ("stop", "length"):
            # genuine completions only: cancelled/timed-out requests also
            # land here as "stop" but must not inflate the throughput
            # that _retry_after derives Retry-After from
            self.requests_completed += 1
        if (not self._cleanup
                and self._faults.fire("crash_before_done") is not None):
            # simulated process death in the journal's at-least-once
            # window: the request completed but its tombstone was never
            # written, so a successor engine must replay it
            raise FaultError(
                "injected crash before journal tombstone "
                f"(rid {s.req.rid})"
            )
        if self._journal is not None:
            self._journal.record_done(s.req.rid)
        if s.req.stream is not None:
            s.req.stream.put(None)
        self._free_slot_state(slot)

    def _free_slot_state(self, slot: int) -> None:
        """Release a slot's engine-side state (sampling rows, pages)
        without touching the request's terminal fields."""
        if (self._prefilling is not None
                and self._prefilling.slot == slot):
            # the request died mid-chunked-prefill (cancel / deadline /
            # fail_all): every finish path funnels through here, so
            # clearing the plan here is what guarantees no orphaned
            # chunk ever runs for a freed slot
            self._prefilling = None
        self._slots[slot] = _Slot()
        self.active[slot] = False
        if self._slot_adapter[slot] is not None:
            # the slot's adapter row leaves the batched tree; the
            # request's registry reference (if still alive — parked)
            # is _adapter_refs' business, not the slot's
            self._slot_adapter[slot] = None
            self._blora_dirty = True
        self._dosample[slot] = False  # idle rows decode deterministic garbage
        self._penalty[slot] = 1.0
        self.seen = self.seen.at[slot].set(False)
        if self.paged:
            self._release_slot_pages(slot)

    def _reset_state(self) -> None:
        """Rebuild the (possibly donated-away) cache after a failed decode
        so the engine can keep serving new requests."""
        self.cache = self._make_pool()
        if self.speculative:
            self.dcache = self._make_pool(force_dense=True)
        self.cur = jnp.zeros((self.n_slots,), jnp.int32)
        self.seen = jnp.zeros(
            (self.n_slots, self.config.vocab_size), jnp.bool_
        )
        self._penalty[:] = 1.0
        self.active[:] = False
        self._preempted.clear()  # blobs reference the old pool's layout
        self._prefilling = None  # a half-run chunk plan died with the pool
        self._slot_adapter = [None] * self.n_slots
        self._blora, self._blora_dirty = None, True
        if self.paged:
            from bigdl_tpu import kvpaged
            from bigdl_tpu.serving.radix import RadixPrefixCache

            # rebuild pool + radix together (cached nodes reference the
            # old pool's pages); hit/eviction counters survive — they
            # are engine totals, not cache state
            self._pool = kvpaged.PagePool(self.n_pages)
            self._free_pages = self._pool.free
            self._page_ref = self._pool.ref
            self.radix = RadixPrefixCache(self.page_size, self._pool)
            if self._pager is not None:
                # resident adapters referenced the dead pool's pages;
                # drop residency (host copies in the registry survive —
                # the next admission re-pages-in) and retarget the pool
                self._pager.reset(self._pool)
            self._slot_pages = [[] for _ in range(self.n_slots)]
            self._slot_written = [0] * self.n_slots
            self._slot_pos = [0] * self.n_slots
            self._bt_host[:] = 0
            self._bt_dirty = True

    def cancel(self, req: Request) -> None:
        """Thread-safe: stop generating for a request whose consumer is
        gone (stop-string cut, client disconnect). The slot frees on the
        engine thread's next step."""
        if req.done:  # lost the race with a normal finish: nothing to do
            return
        self._cancelled[req.rid] = req

    def _reap_cancelled(self) -> None:
        # prune marks that lost the cancel-vs-finish race (the request
        # finished between the caller's done-check and its cancel()).
        # list() snapshots the items atomically (C-level copy) — handler
        # threads insert concurrently, and iterating the live dict here
        # would intermittently die with 'dict changed size'.
        for rid, q in list(self._cancelled.items()):
            if q.done:
                self._cancelled.pop(rid, None)
        for i, s in enumerate(self._slots):
            if s.req is not None and s.req.rid in self._cancelled:
                self._cancelled.pop(s.req.rid, None)
                self._finish(i, "stop", counted=False)

    def _inject_nan(self, lps: "np.ndarray") -> "np.ndarray":
        """Chaos hook shared by the plain and speculative decode paths:
        when nan_logits is armed, poison the victim rows' host-side
        logprobs as if the model had produced non-finite values for
        them (the quarantine guard downstream must catch it)."""
        f = self._faults.fire("nan_logits")
        if f is None:
            return lps
        lps = lps.copy()
        victims = f.get("slots")
        if victims is None:
            act = np.nonzero(self.active)[0]
            victims = [int(act[0])] if act.size else []
        for v in victims:
            lps[v] = np.nan
        return lps

    def _bump(self, counter: str) -> None:
        """Increment an overload counter race-free: requests_shed and
        request_timeouts are bumped from HTTP handler threads AND the
        engine thread, and `+=` on an attribute is not atomic."""
        with self._stat_lock:
            setattr(self, counter, getattr(self, counter) + 1)

    def _expire_queued(self, req: Request, which: str, now: float) -> None:
        """Terminal handling for a request that expired before admission:
        queue-deadline → shed (retryable 503), total deadline → timeout.
        One copy — the admission pop and the saturation sweep must never
        drift in message or counter discipline."""
        if which == "queue_deadline_s":
            self._shed_request(req, "queue_deadline", (
                f"queue deadline: waited {now - req.submit_ts:.2f}s > "
                f"queue_deadline_s={req.queue_deadline_s}"
            ))
        else:
            self._finish_detached(
                req, "timeout",
                error=f"deadline_s={req.deadline_s} exceeded before "
                "admission",
            )
            self._bump("request_timeouts")

    def _sweep_preempted(self) -> None:
        """Drop parked requests whose client cancelled or whose deadline
        expired, at ANY depth of the deque — a blocked head must not
        keep an already-dead request (and its host KV blob) parked
        indefinitely behind it. Engine-thread only, like _preempted."""
        if not self._preempted:
            return
        now = self._clock()
        keep: "collections.deque[_Preempted]" = collections.deque()
        for entry in self._preempted:
            req = entry.req
            if req.rid in self._cancelled:
                self._cancelled.pop(req.rid, None)
                self._finish_detached(req, "stop")
                continue
            if self._expired(req, now) is not None:
                # finish BEFORE the bump: once done is set, a racing
                # server-side wait timeout sees it and stands down, so
                # the counter records the request exactly once
                self._finish_detached(
                    req, "timeout",
                    error=f"deadline_s={req.deadline_s} exceeded "
                    "while preempted",
                )
                self._bump("request_timeouts")
                continue
            keep.append(entry)
        self._preempted = keep

    def _sweep_queue(self) -> None:
        """Drop requests that died while still WAITING in the queue —
        expired deadlines AND cancelled clients — even when no slot
        frees: a saturated engine must not 429 new clients over a queue
        of already-dead work. A deadline-dead request's client gets its
        promised fast 503 instead of waiting for a slot that may be
        minutes away; a cancelled entry (server timeout, disconnect)
        stops counting against max_queue the next step, not when a slot
        eventually frees."""
        if not self._deadlines_seen and not self._cancelled:
            return
        now = self._clock()
        # the paged OOM-retry slot waits like a queue entry and gets the
        # same dead-work treatment — _admit can return early (blocked
        # preemption resume) for many steps without ever popping it
        if self._waiting is not None:
            req = self._waiting
            if req.rid in self._cancelled:
                self._waiting = None
                self._cancelled.pop(req.rid, None)
                self._finish_detached(req, "stop")
            else:
                which = self._expired(req, now)
                if which is not None:
                    self._waiting = None
                    self._expire_queued(req, which, now)
        if self._queue.empty():
            return
        expired: list[tuple[Request, str]] = []
        cancelled: list[Request] = []
        with self._queue.mutex:  # surgery on the deque under the queue's
            # own lock; qsize()/put() stay consistent, FIFO order is
            # kept. One partition pass: each verdict computed once, and
            # the mutex (which blocks handler-thread submits) is held for
            # a single scan
            q = self._queue.queue
            keep = []
            for r in q:
                which = self._expired(r, now)
                if r.rid in self._cancelled:
                    cancelled.append(r)
                elif which is not None:
                    expired.append((r, which))
                else:
                    keep.append(r)
            if expired or cancelled:
                q.clear()
                q.extend(keep)
        for req in cancelled:  # journal/stream work outside the lock
            self._cancelled.pop(req.rid, None)
            self._finish_detached(req, "stop")
        for req, which in expired:
            self._expire_queued(req, which, now)

    def _reap_deadlines(self) -> None:
        """Kill in-flight requests past their total wall-clock budget:
        partial output is delivered, finish_reason records 'timeout'."""
        now = self._clock()
        for i, s in enumerate(self._slots):
            if s.req is None or s.req.deadline_s is None:
                continue
            if s.req.rid in self._cancelled:
                # a server-side wait timeout got here first: it already
                # counted the timeout, and the next _reap_cancelled will
                # free the slot — bumping again would double-count the
                # one request in request_timeouts_total
                continue
            if now - s.req.submit_ts > s.req.deadline_s:
                s.req.error = (
                    f"deadline_s={s.req.deadline_s} exceeded after "
                    f"{len(s.req.out_tokens)} tokens"
                )
                # finish (sets done) BEFORE the bump: a racing _wait
                # timeout stands down on done, so one timed-out request
                # is never counted twice
                self._finish(i, "timeout")
                self._bump("request_timeouts")

    def step(self) -> bool:
        """Admit queued requests, advance every active slot one token.
        Returns True if any work remains."""
        f = self._faults.fire("slow_step")
        if f is not None:  # injected device stall (serving/faults.py)
            time.sleep(float(f.get("seconds", 0.05)))
        self._reap_cancelled()
        self._reap_preempt_requests()
        self._reap_deadlines()
        self._sweep_preempted()
        self._sweep_queue()
        self._admit()
        self._advance_prefill()  # at most one chunk per step
        if self.paged:
            # reserve for the CURRENT ladder K (== draft_k when not
            # adaptive): after a downshift the round writes at most
            # _cur_k tokens before rollback, so tighter is still safe
            self._ensure_decode_pages(
                self._cur_k if self.speculative else 1
            )
            if self._bt_dirty:
                self.cache = dataclasses.replace(
                    self.cache, block_tables=jnp.asarray(self._bt_host)
                )
                self._bt_dirty = False
        if not self.active.any():
            return (not self._queue.empty() or self._waiting is not None
                    or bool(self._preempted)
                    or self._prefilling is not None)
        self._rng, k = jax.random.split(self._rng)
        if self.speculative:
            return self._step_speculative(k)
        t0 = self._clock()
        try:
            nxt, lps, top, self.cache, self.seen = self._decode(
                self.model.params, self.cur, self.cache, k,
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._topp), jnp.asarray(self._dosample),
                self.seen, jnp.asarray(self._penalty),
                lora=self._gather_blora(),
            )
        except Exception:
            # the donated cache buffer is gone — rebuild before re-raising
            # (the server's guard fails the in-flight requests)
            self.fail_all("decode step failed")
            self._reset_state()
            raise
        self.cur = nxt
        toks = np.asarray(nxt)
        lps_h = self._inject_nan(np.asarray(lps))
        tops_h = None
        if top is not None:
            tops_h = (np.asarray(top[0]), np.asarray(top[1]))
        # the np.asarray fetches above are the host sync: the step's
        # device work is really done here, so the duration is honest
        self._note_decode_step(t0)
        for i in np.nonzero(self.active)[0]:
            i = int(i)
            s = self._slots[i]
            if not np.isfinite(lps_h[i]):
                # non-finite logits guard: quarantine the ONE poisoned
                # slot (its sampled token/logprob are garbage) instead of
                # letting the exception path fail_all the whole batch —
                # per-row decode means other slots' math is untouched
                s.req.error = (
                    "non-finite logits in decode step; request "
                    "quarantined (other slots unaffected)"
                )
                self._finish(i, "error")
                continue
            s.remaining -= 1
            if self.paged:
                self._slot_pos[i] += 1
            alt = None
            if tops_h is not None:
                alt = {int(t): float(l)
                       for t, l in zip(tops_h[0][i], tops_h[1][i])}
            self._emit(i, int(toks[i]), float(lps_h[i]), alt)
        return True

    def _note_decode_step(self, t0: float) -> None:
        """Per-step phase accounting: duration histogram + the engine
        track's span/occupancy counter (tid 0 — batch-level, not
        per-request)."""
        t1 = self._clock()
        self.decode_step_seconds.observe(t1 - t0)
        tr = self.tracer
        if tr is not None and tr.enabled:
            busy = int(self.active.sum())
            tr.complete("decode_step", t0, t1 - t0, tid=0, cat="engine",
                        occupancy=busy, slots=self.n_slots,
                        queue_depth=self._queue.qsize())
            tr.counter("batch", ts=t1, occupancy=busy,
                       queued=self._queue.qsize(),
                       preempted=len(self._preempted))

    def _step_speculative(self, k) -> bool:
        """Draft-K-then-verify round: each live slot emits 1..draft_k
        tokens (its accepted prefix + the target's bonus token)."""
        if self._spec_exec is not None:  # pre-compiled ladder program
            fn = self._spec_exec[self._cur_k]
        else:
            fn = functools.partial(self._spec_decode, self._cur_k)
        kw = {}
        if self.adapters is not None:
            # verify with the slots' adapters applied (None when no
            # active slot carries one). AOT executables have no lora
            # slot, but adapter engines never build them (_spec_exec
            # stays None — the jit path retraces per tree structure)
            kw["lora"] = self._gather_blora()
        t0 = self._clock()
        try:
            (choice, lp_all, n_acc, cur2, self.cache, self.dcache,
             self.seen) = fn(
                self.model.params, self._draft_params, self.cur,
                self.cache, self.dcache, k,
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._topp), jnp.asarray(self._dosample),
                self.seen, jnp.asarray(self._penalty),
                **kw,
            )
        except Exception:
            self.fail_all("speculative decode step failed")
            self._reset_state()
            raise
        self.cur = cur2
        choice_h = np.asarray(choice)
        lp_h = self._inject_nan(np.asarray(lp_all))
        n_acc_h = np.asarray(n_acc)
        self._note_decode_step(t0)
        self.spec_rounds += 1
        if self.adaptive_draft:
            self._adapt_draft_k(n_acc_h[self.active])
        for i in np.nonzero(self.active)[0]:
            i = int(i)
            s = self._slots[i]
            if not np.all(np.isfinite(lp_h[i, : int(n_acc_h[i]) + 1])):
                # same quarantine as the plain path: one poisoned row
                # must not take the batch down
                s.req.error = (
                    "non-finite logits in speculative verify; request "
                    "quarantined (other slots unaffected)"
                )
                self._finish(i, "error")
                continue
            if self.paged:  # mirror the post-rollback pool position
                self._slot_pos[i] += int(n_acc_h[i]) + 1
            for t in range(int(n_acc_h[i]) + 1):
                s.remaining -= 1
                self.spec_emitted += 1
                self._emit(i, int(choice_h[i, t]), float(lp_h[i, t]))
                if not self.active[i]:  # EOS or budget hit mid-round
                    break
        return True

    def _adapt_draft_k(self, n_acc: np.ndarray) -> None:
        """Steer the draft length along the compiled-K ladder from an
        EMA of the per-round acceptance fraction. Output is unchanged by
        construction (speculative decoding is exact at any K); only the
        draft-compute : emitted-token ratio moves."""
        if n_acc.size == 0:
            return
        frac = float(np.mean(n_acc)) / max(self._cur_k - 1, 1)
        self._accept_ema = (
            frac if self._accept_ema is None
            else 0.7 * self._accept_ema + 0.3 * frac
        )
        idx = self._k_ladder.index(self._cur_k)
        if self._accept_ema < 0.35 and idx > 0:
            self._cur_k = self._k_ladder[idx - 1]
            self._accept_ema = None  # re-measure at the new K
        elif self._accept_ema > 0.75 and idx < len(self._k_ladder) - 1:
            self._cur_k = self._k_ladder[idx + 1]
            self._accept_ema = None

    def _fail_request(self, req: Request, msg: str) -> None:
        """Terminal failure for a request not (or no longer) in a slot."""
        self._finish_detached(req, "error", error=msg)

    def fail_all(self, msg: str) -> None:
        """Mark every in-flight and queued request failed (engine-thread
        crash path — streams get their sentinel so clients unblock).
        Injected crash points are suppressed for the duration: cleanup
        after a crash must not itself crash (an armed crash_before_done
        with charges left would otherwise kill the engine thread)."""
        self._cleanup = True
        try:
            for i, s in enumerate(self._slots):
                if s.req is None:
                    continue
                if s.req.done:
                    # crashed INSIDE _finish (injected crash_before_done):
                    # the request completed — deliver the sentinel it
                    # never got and free the slot, but do NOT rewrite its
                    # terminal state or journal a tombstone; the whole
                    # point of the crash window is that a successor
                    # engine replays this request (at-least-once)
                    if s.req.stream is not None:
                        s.req.stream.put(None)
                    self._free_slot_state(i)
                    continue
                s.req.error = msg
                self._finish(i, "error")
            if self._waiting is not None:
                req, self._waiting = self._waiting, None
                self._fail_request(req, msg)
            while self._preempted:  # parked requests die with the engine
                self._fail_request(self._preempted.popleft().req, msg)
            while True:
                try:
                    req = self._queue.get_nowait()
                except queue.Empty:
                    break
                self._fail_request(req, msg)
            self.active[:] = False
        finally:
            self._cleanup = False

    def run_until_idle(self, max_steps: int = 100000) -> None:
        for _ in range(max_steps):
            if not self.step():
                return

    # ---- graceful shutdown (docs/serving.md) -------------------------------

    def begin_drain(self) -> None:
        """Stop admitting (new submits shed as "draining" -> 503 +
        Retry-After) while in-flight and queued work keeps stepping.
        Thread-safe; whoever steps the engine keeps stepping it."""
        self._draining = True

    def idle(self) -> bool:
        """No accepted-but-unfinished work remains. Based on the
        in-flight charge counter, not container emptiness — a request
        mid-admission is momentarily in no container but still holds
        its charge, so a concurrent drain poll cannot miss it."""
        with self._stat_lock:
            return self._inflight == 0

    def drain(self, timeout_s: Optional[float] = None) -> bool:
        """begin_drain + step to completion from the CALLING thread —
        for engines driven without an _EngineThread (the ApiServer
        instead begin_drain()s and lets its worker thread finish the
        work). Returns True when fully drained; False on timeout, with
        the unfinished requests left pending (journaled engines replay
        them at the next start — the crash-recovery path is the
        fallback, not the plan)."""
        self.begin_drain()
        deadline = (None if timeout_s is None
                    else self._clock() + timeout_s)
        while not self.idle():
            if deadline is not None and self._clock() > deadline:
                return False
            self.step()
        return True

    def close(self) -> None:
        """Flush, COMPACT, and detach the journal (and close the
        request log). Call only after the stepping thread has stopped:
        compaction os.replace()s the file under any live append handle.
        After a clean drain the rewrite holds zero entries — the next
        start replays nothing; after a timed-out drain it holds exactly
        the unfinished tail. Idempotent."""
        if self._request_log is not None:
            self._request_log.close()
        if self._journal is None:
            return
        from bigdl_tpu.serving.journal import RequestJournal

        path = self._journal.path
        self._journal.close()
        self._journal = None
        RequestJournal.compact(path)

    # ---- observability helpers (serving/metrics.py renders these) ----------

    def uptime_seconds(self) -> float:
        """Engine age in its own clock domain (simulated clocks report
        simulated uptime — by design)."""
        return max(self._clock() - self._t_start, 0.0)

    def page_leaks(self) -> int:
        """Pages whose refcount disagrees with their accounted holders
        (slot block tables + radix cache nodes) plus any page neither
        free nor held at all. 0 is the invariant; the sim report and
        the chaos tests gate on it at drain."""
        if not self.paged:
            return 0
        held = [0] * self.n_pages
        for pages in self._slot_pages:
            for pg in pages:
                held[pg] += 1
        for node in self.radix.nodes():
            held[node.page] += 1
        if self._pager is not None:
            for pg in self._pager.held_pages():
                held[pg] += 1
        return sum(1 for pg in range(1, self.n_pages)
                   if self._page_ref[pg] != held[pg])

    def kv_utilization(self) -> float:
        """Fraction of the KV pool holding live state: allocated pages
        over the allocatable pool (paged; page 0 is scratch), or written
        positions over total row capacity (dense). Family caches without
        a standard pos vector report 0 rather than guessing."""
        if self.paged:
            cap = self.n_pages - 1
            return (cap - len(self._free_pages)) / max(cap, 1)
        # HOST-side estimate only: reading cache.pos here would race the
        # decode jit's cache donation (the buffers are deleted for most
        # of every step, and /metrics scrapes from a handler thread).
        # Active slots' written content ≈ prompt + emitted tokens; freed
        # slots count zero (their stale device pos is a ghost).
        used = 0
        for i, s in enumerate(self._slots):
            if s.req is not None and self.active[i]:
                used += min(len(s.req.prompt) + len(s.req.out_tokens),
                            self.max_len)
        return used / max(self.n_slots * self.max_len, 1)
