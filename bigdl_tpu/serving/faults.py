"""Deterministic fault-injection harness for the serving engine.

The reference stack's overload behavior is only exercisable against real
failing hardware; here every recovery path in the engine runs on CPU
under *injected* faults, so the chaos suite is an ordinary fast pytest
module. The engine threads a `FaultInjector` through its hot paths as a
no-op-by-default hook table: an unarmed injector costs one dict lookup
per call site and changes nothing.

Injection points (the strings the engine fires):

==================  =======================================================
point               effect when armed
==================  =======================================================
``alloc_page``      the next paged-pool page allocation fails (returns no
                    page), as if the pool were exhausted — drives the
                    preemption path without needing a real page storm
``nan_logits``      one decode step's host-side logprobs for a victim slot
                    become NaN, as if the model produced non-finite logits
                    for that row — drives the quarantine guard. payload:
                    ``slots=[...]`` picks victims (default: first active)
``slow_step``       ``engine.step()`` sleeps before doing work, as if the
                    device stalled. payload: ``seconds=float``
``crash_before_done``  ``_finish`` raises :class:`FaultError` after the
                    request is complete but BEFORE its journal tombstone
                    is written — the crash-recovery window the journal
                    replay must cover
``adapter_load_corrupt``  the next LoRA adapter load fails as if the
                    artifact were corrupt (structured AdapterError,
                    serving/adapters.py) — the request naming it must
                    finish "error" without taking the batch down
``adapter_page_in_stall``  the next device page-in of an adapter's
                    weights stalls (AdapterPager.ensure raises a
                    structured AdapterError) — quarantines exactly the
                    one request naming the tenant, never fail_all
==================  =======================================================

Arming is deterministic by construction: ``arm(point, times=N, after=M)``
fires on eligible calls M+1 .. M+N. The optional ``prob`` mode draws from
a seeded ``random.Random`` so even probabilistic chaos replays exactly.

Usage::

    inj = FaultInjector(seed=7)
    inj.arm("alloc_page", times=1, after=2)   # 3rd allocation fails
    eng = InferenceEngine(model, paged=True, faults=inj)
"""

from __future__ import annotations

import dataclasses
import random
import threading
from collections import defaultdict
from typing import Optional

POINTS = ("alloc_page", "nan_logits", "slow_step", "crash_before_done",
          "adapter_load_corrupt", "adapter_page_in_stall")


class FaultError(RuntimeError):
    """Raised by an injected crash point (never by real engine code)."""


@dataclasses.dataclass
class _Arm:
    times: int  # firings remaining; -1 = unlimited
    after: int  # eligible calls to skip first
    prob: float  # per-eligible-call firing probability
    payload: dict


class FaultInjector:
    """Seedable hook table; thread-safe (handler threads and the engine
    thread may hit different points concurrently).

    `points` is a class attribute so other subsystems can reuse the
    arm/disarm/fire discipline with their own injection-point table
    (utils/diskfaults.py does, for storage faults)."""

    points = POINTS

    def __init__(self, seed: int = 0):
        self._rng = random.Random(seed)
        self._arms: dict[str, _Arm] = {}
        self._lock = threading.Lock()
        # observability for tests: how often each point was reached/fired
        self.seen: dict[str, int] = defaultdict(int)
        self.fired: dict[str, int] = defaultdict(int)

    def arm(self, point: str, times: int = 1, after: int = 0,
            prob: float = 1.0, **payload) -> "FaultInjector":
        """Arm `point` to fire `times` times (-1 = forever) after skipping
        the first `after` eligible calls. Extra kwargs ride along as the
        payload dict `fire` returns. Returns self for chaining."""
        if point not in self.points:
            raise ValueError(
                f"unknown injection point {point!r}; known: {self.points}"
            )
        with self._lock:
            self._arms[point] = _Arm(times=times, after=after, prob=prob,
                                     payload=dict(payload))
        return self

    def disarm(self, point: Optional[str] = None) -> None:
        with self._lock:
            if point is None:
                self._arms.clear()
            else:
                self._arms.pop(point, None)

    def fire(self, point: str) -> Optional[dict]:
        """Engine-side hook: returns the arm's payload dict when the fault
        triggers, None otherwise. Unarmed points return None in O(1)."""
        with self._lock:
            self.seen[point] += 1
            a = self._arms.get(point)
            if a is None:
                return None
            if a.after > 0:
                a.after -= 1
                return None
            if a.times == 0:
                return None
            if a.prob < 1.0 and self._rng.random() >= a.prob:
                return None
            if a.times > 0:
                a.times -= 1
            self.fired[point] += 1
            return dict(a.payload)


class NullFaultInjector(FaultInjector):
    """The engine's default: every point unarmed, arming forbidden (a
    shared module-level instance must stay inert). `fire` is overridden
    to a bare None so production engines pay no lock acquisition and
    share no counter state through the module-level instance."""

    def arm(self, *a, **k):  # pragma: no cover - guard rail
        raise RuntimeError(
            "this is the shared no-op injector; construct your own "
            "FaultInjector and pass it to the engine via faults="
        )

    def fire(self, point: str) -> Optional[dict]:
        return None


NULL_INJECTOR = NullFaultInjector()
