"""Serving metrics: counters / histogram / gauges with a Prometheus
text-format endpoint.

The reference exposes Prometheus through its vLLM fork
(vllm/xpu/entrypoints/openai/api_server.py, PROMETHEUS_MULTIPROC_DIR in
/root/reference); this is the stdlib-only equivalent for our engine —
the /metrics endpoint renders the standard exposition format, so a
Prometheus scraper pointed at the server just works.
"""

from __future__ import annotations

import threading
from collections import defaultdict

# request latency histogram bucket upper bounds (seconds)
_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Metrics:
    def __init__(self, engine=None):
        self._lock = threading.Lock()
        self.engine = engine
        self.requests = defaultdict(int)  # (endpoint, status) -> count
        self.tokens_generated = 0
        self.requests_failed = 0
        self.hist_counts = defaultdict(lambda: [0] * (len(_BUCKETS) + 1))
        self.hist_sum = defaultdict(float)

    # -- recording ----------------------------------------------------------
    def observe_request(self, endpoint: str, status: int, seconds: float):
        with self._lock:
            self.requests[(endpoint, status)] += 1
            if status >= 500:
                self.requests_failed += 1
            counts = self.hist_counts[endpoint]
            for i, ub in enumerate(_BUCKETS):
                if seconds <= ub:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            self.hist_sum[endpoint] += seconds

    def count_tokens(self, n: int):
        with self._lock:
            self.tokens_generated += n

    # -- exposition ---------------------------------------------------------
    def render(self) -> str:
        lines = [
            "# HELP bigdl_tpu_requests_total HTTP requests by endpoint/status",
            "# TYPE bigdl_tpu_requests_total counter",
        ]
        with self._lock:
            for (ep, status), n in sorted(self.requests.items()):
                lines.append(
                    f'bigdl_tpu_requests_total{{endpoint="{ep}",'
                    f'status="{status}"}} {n}'
                )
            lines += [
                "# HELP bigdl_tpu_tokens_generated_total tokens emitted",
                "# TYPE bigdl_tpu_tokens_generated_total counter",
                f"bigdl_tpu_tokens_generated_total {self.tokens_generated}",
                "# HELP bigdl_tpu_requests_failed_total 5xx responses",
                "# TYPE bigdl_tpu_requests_failed_total counter",
                f"bigdl_tpu_requests_failed_total {self.requests_failed}",
                "# HELP bigdl_tpu_request_seconds request latency",
                "# TYPE bigdl_tpu_request_seconds histogram",
            ]
            for ep, counts in sorted(self.hist_counts.items()):
                cum = 0
                for i, ub in enumerate(_BUCKETS):
                    cum += counts[i]
                    lines.append(
                        f'bigdl_tpu_request_seconds_bucket{{endpoint="{ep}",'
                        f'le="{ub}"}} {cum}'
                    )
                cum += counts[-1]
                lines.append(
                    f'bigdl_tpu_request_seconds_bucket{{endpoint="{ep}",'
                    f'le="+Inf"}} {cum}'
                )
                lines.append(
                    f'bigdl_tpu_request_seconds_sum{{endpoint="{ep}"}} '
                    f"{self.hist_sum[ep]:.6f}"
                )
                lines.append(
                    f'bigdl_tpu_request_seconds_count{{endpoint="{ep}"}} {cum}'
                )
        if self.engine is not None:
            busy = int(self.engine.active.sum())
            lines += [
                "# HELP bigdl_tpu_busy_slots decode slots in use",
                "# TYPE bigdl_tpu_busy_slots gauge",
                f"bigdl_tpu_busy_slots {busy}",
                "# HELP bigdl_tpu_total_slots decode slot pool size",
                "# TYPE bigdl_tpu_total_slots gauge",
                f"bigdl_tpu_total_slots {self.engine.n_slots}",
                "# HELP bigdl_tpu_queue_depth requests waiting for a slot",
                "# TYPE bigdl_tpu_queue_depth gauge",
                f"bigdl_tpu_queue_depth {self.engine._queue.qsize()}",
            ]
            if self.engine.paged:
                lines += [
                    "# HELP bigdl_tpu_free_pages allocatable KV pages",
                    "# TYPE bigdl_tpu_free_pages gauge",
                    f"bigdl_tpu_free_pages {len(self.engine._free_pages)}",
                    "# HELP bigdl_tpu_prefix_hits_total full-page prefix "
                    "cache hits",
                    "# TYPE bigdl_tpu_prefix_hits_total counter",
                    f"bigdl_tpu_prefix_hits_total {self.engine.prefix_hits}",
                    "# HELP bigdl_tpu_prefix_partial_hits_total sub-page "
                    "prefix copies",
                    "# TYPE bigdl_tpu_prefix_partial_hits_total counter",
                    f"bigdl_tpu_prefix_partial_hits_total "
                    f"{self.engine.prefix_partial_hits}",
                    "# HELP bigdl_tpu_prefix_tokens_reused_total prompt "
                    "tokens served from copied KV instead of prefill",
                    "# TYPE bigdl_tpu_prefix_tokens_reused_total counter",
                    f"bigdl_tpu_prefix_tokens_reused_total "
                    f"{self.engine.prefix_tokens_reused}",
                ]
            if self.engine.speculative:
                lines += [
                    "# HELP bigdl_tpu_spec_rounds_total verify rounds run",
                    "# TYPE bigdl_tpu_spec_rounds_total counter",
                    f"bigdl_tpu_spec_rounds_total {self.engine.spec_rounds}",
                    "# HELP bigdl_tpu_spec_emitted_total tokens emitted by "
                    "verify rounds",
                    "# TYPE bigdl_tpu_spec_emitted_total counter",
                    f"bigdl_tpu_spec_emitted_total {self.engine.spec_emitted}",
                    "# HELP bigdl_tpu_spec_draft_k current draft length "
                    "(ladder-steered when adaptive_draft)",
                    "# TYPE bigdl_tpu_spec_draft_k gauge",
                    f"bigdl_tpu_spec_draft_k {self.engine._cur_k}",
                ]
        return "\n".join(lines) + "\n"
