"""Serving metrics: counters / histogram / gauges with a Prometheus
text-format endpoint.

The reference exposes Prometheus through its vLLM fork
(vllm/xpu/entrypoints/openai/api_server.py, PROMETHEUS_MULTIPROC_DIR in
/root/reference); this is the stdlib-only equivalent for our engine —
the /metrics endpoint renders the standard exposition format, so a
Prometheus scraper pointed at the server just works.
"""

from __future__ import annotations

import threading
from collections import defaultdict

# request latency histogram bucket upper bounds (seconds)
_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

# training steps run minutes on big jobs: the request buckets would pile
# everything into +Inf
_STEP_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                 120.0, 300.0, 600.0)

# per-token / per-step phase latencies live in milliseconds: the request
# buckets would flatten every inter-token-latency distribution into the
# bottom bucket (docs/observability.md)
FAST_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                0.5, 1.0, 2.5, 5.0)

# finish reasons ALWAYS rendered (zero-valued series keep dashboards and
# the drift check stable); reasons outside this set render as seen
FINISH_REASONS = ("stop", "length", "error", "shed", "timeout", "invalid")


def _verify_failures() -> int:
    """Process-wide checkpoint verification failure count (lazy import:
    metrics must stay importable without dragging the convert stack)."""
    from bigdl_tpu.utils.durability import VERIFY_FAILURES

    return VERIFY_FAILURES.value


class Counter:
    """Process-wide thread-safe counter for the module-level registry
    (same shape as durability.VERIFY_FAILURES, kept local so this
    module stays stdlib-only)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Minimal lock-free Prometheus histogram: one writer (the engine
    thread observes), any reader (a racing render sees a value at most
    one observation stale — fine for scraping)."""

    def __init__(self, buckets=_BUCKETS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.sum = 0.0

    def observe(self, x: float) -> None:
        for i, ub in enumerate(self.buckets):
            if x <= ub:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.sum += x

    def render_series(self, name: str, label: str = "") -> list:
        """The bucket/sum/count sample lines only (no HELP/TYPE) —
        labelled histogram families emit one HELP/TYPE header over many
        series. `label` is a preformatted 'key="value",' prefix."""
        lines = []
        cum = 0
        for i, ub in enumerate(self.buckets):
            cum += self.counts[i]
            lines.append(f'{name}_bucket{{{label}le="{ub}"}} {cum}')
        cum += self.counts[-1]
        lines.append(f'{name}_bucket{{{label}le="+Inf"}} {cum}')
        suffix = f"{{{label[:-1]}}}" if label else ""
        lines.append(f"{name}_sum{suffix} {self.sum:.6f}")
        lines.append(f"{name}_count{suffix} {cum}")
        return lines

    def render(self, name: str, help_text: str) -> list:
        return [f"# HELP {name} {help_text}",
                f"# TYPE {name} histogram"] + self.render_series(name)


# ---------------------------------------------------------------------------
# training-supervisor registry (train/supervisor.py bumps these; the
# registry is process-wide like VERIFY_FAILURES, so a serving process
# that also runs finetuning — or a scrape of the trainer's own metrics
# endpoint — sees the training health without a second registry)
# ---------------------------------------------------------------------------

TRAIN_ANOMALIES = Counter()             # guarded steps found anomalous
TRAIN_STEPS_SKIPPED = Counter()         # updates discarded (state kept)
TRAIN_ROLLBACKS = Counter()             # restores from last-good ckpt
TRAIN_EMERGENCY_CHECKPOINTS = Counter()  # SIGTERM-boundary saves
TRAIN_WATCHDOG_ABORTS = Counter()       # hung-step exits
TRAIN_STEP_SECONDS = Histogram(buckets=_STEP_BUCKETS)

_TRAIN_COUNTER_SERIES = (
    ("bigdl_tpu_train_anomalies_total",
     "training steps flagged anomalous (NaN/inf loss or grad-norm, "
     "EMA loss spike)", TRAIN_ANOMALIES),
    ("bigdl_tpu_train_steps_skipped_total",
     "anomalous steps skipped with optimizer state untouched",
     TRAIN_STEPS_SKIPPED),
    ("bigdl_tpu_train_rollbacks_total",
     "rollbacks to the last good checkpoint after consecutive "
     "anomalies", TRAIN_ROLLBACKS),
    ("bigdl_tpu_train_emergency_checkpoints_total",
     "preemption-signal emergency checkpoints", TRAIN_EMERGENCY_CHECKPOINTS),
    ("bigdl_tpu_train_watchdog_aborts_total",
     "hung-step watchdog aborts", TRAIN_WATCHDOG_ABORTS),
)


def render_train_series() -> list:
    lines = []
    for name, help_text, c in _TRAIN_COUNTER_SERIES:
        lines += [f"# HELP {name} {help_text}",
                  f"# TYPE {name} counter",
                  f"{name} {c.value}"]
    lines += TRAIN_STEP_SECONDS.render(
        "bigdl_tpu_train_step_seconds",
        "supervised training step wall-clock (incl. host loss fetch)",
    )
    return lines


def render_build_info() -> list:
    """`bigdl_tpu_build_info` gauge: constant 1 with the build identity
    as labels — the standard Prometheus idiom for joining every other
    series against a version during a rollout."""
    from bigdl_tpu import __version__

    try:
        import jax

        jaxv = jax.__version__
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        jaxv = "unknown"
    try:
        from bigdl_tpu.convert.low_bit import FORMAT_VERSION

        fmt = str(FORMAT_VERSION)
    except Exception:  # pragma: no cover - convert stack unavailable
        fmt = "unknown"
    return [
        "# HELP bigdl_tpu_build_info build identity (constant 1; "
        "version labels)",
        "# TYPE bigdl_tpu_build_info gauge",
        f'bigdl_tpu_build_info{{version="{__version__}",'
        f'jax_version="{jaxv}",format_version="{fmt}"}} 1',
    ]


class Metrics:
    def __init__(self, engine=None):
        self._lock = threading.Lock()
        self.engine = engine
        self.requests = defaultdict(int)  # (endpoint, status) -> count
        self.tokens_generated = 0
        self.requests_failed = 0
        self.hist = defaultdict(Histogram)  # endpoint -> latency histogram

    # -- recording ----------------------------------------------------------
    def observe_request(self, endpoint: str, status: int, seconds: float):
        with self._lock:
            self.requests[(endpoint, status)] += 1
            if status >= 500 and status != 503:
                # 503 is deliberate load shedding (queue deadline,
                # docs/serving.md) — the designed healthy overload
                # response, tracked by bigdl_tpu_requests_shed_total;
                # counting it here would make the failure-rate alert
                # fire on backpressure (and inconsistently: the 429
                # shed path never counted)
                self.requests_failed += 1
            self.hist[endpoint].observe(seconds)

    def count_tokens(self, n: int):
        with self._lock:
            self.tokens_generated += n

    # -- exposition ---------------------------------------------------------
    def render(self) -> str:
        lines = [
            "# HELP bigdl_tpu_requests_total HTTP requests by endpoint/status",
            "# TYPE bigdl_tpu_requests_total counter",
        ]
        with self._lock:
            for (ep, status), n in sorted(self.requests.items()):
                lines.append(
                    f'bigdl_tpu_requests_total{{endpoint="{ep}",'
                    f'status="{status}"}} {n}'
                )
            lines += [
                "# HELP bigdl_tpu_tokens_generated_total tokens emitted",
                "# TYPE bigdl_tpu_tokens_generated_total counter",
                f"bigdl_tpu_tokens_generated_total {self.tokens_generated}",
                "# HELP bigdl_tpu_requests_failed_total 5xx responses",
                "# TYPE bigdl_tpu_requests_failed_total counter",
                f"bigdl_tpu_requests_failed_total {self.requests_failed}",
                # artifact durability (utils/durability.py): process-wide
                # count of checkpoint integrity-verification failures —
                # a nonzero here means a load saw corruption (raised or
                # salvaged) and restarts are running on borrowed time
                "# HELP bigdl_tpu_checkpoint_verify_failures_total "
                "checkpoint integrity verification failures",
                "# TYPE bigdl_tpu_checkpoint_verify_failures_total counter",
                f"bigdl_tpu_checkpoint_verify_failures_total "
                f"{_verify_failures()}",
            ]
            lines += render_build_info()
            lines += render_train_series()
            lines += [
                "# HELP bigdl_tpu_request_seconds request latency",
                "# TYPE bigdl_tpu_request_seconds histogram",
            ]
            for ep, hist in sorted(self.hist.items()):
                lines += hist.render_series(
                    "bigdl_tpu_request_seconds", f'endpoint="{ep}",'
                )
        if self.engine is not None:
            busy = int(self.engine.active.sum())
            lines += [
                "# HELP bigdl_tpu_busy_slots decode slots in use",
                "# TYPE bigdl_tpu_busy_slots gauge",
                f"bigdl_tpu_busy_slots {busy}",
                "# HELP bigdl_tpu_total_slots decode slot pool size",
                "# TYPE bigdl_tpu_total_slots gauge",
                f"bigdl_tpu_total_slots {self.engine.n_slots}",
                "# HELP bigdl_tpu_queue_depth requests waiting for a slot",
                "# TYPE bigdl_tpu_queue_depth gauge",
                f"bigdl_tpu_queue_depth {self.engine._queue.qsize()}",
                # overload-protection observability (docs/serving.md):
                # preemption activity, load shedding, and deadline kills
                # are invisible without these — an operator must be able
                # to tell "we truncated output" never happens from graphs
                "# HELP bigdl_tpu_preemptions_total requests swapped to "
                "host RAM under page-pool pressure",
                "# TYPE bigdl_tpu_preemptions_total counter",
                f"bigdl_tpu_preemptions_total {self.engine.preemptions}",
                "# HELP bigdl_tpu_preemption_resumes_total preempted "
                "requests swapped back in and resumed",
                "# TYPE bigdl_tpu_preemption_resumes_total counter",
                f"bigdl_tpu_preemption_resumes_total "
                f"{self.engine.preemption_resumes}",
                "# HELP bigdl_tpu_requests_shed_total requests rejected "
                "at/behind admission (queue bound or queue deadline)",
                "# TYPE bigdl_tpu_requests_shed_total counter",
                f"bigdl_tpu_requests_shed_total {self.engine.requests_shed}",
                "# HELP bigdl_tpu_request_timeouts_total requests killed "
                "by a deadline or server wait timeout",
                "# TYPE bigdl_tpu_request_timeouts_total counter",
                f"bigdl_tpu_request_timeouts_total "
                f"{self.engine.request_timeouts}",
                "# HELP bigdl_tpu_preempted_waiting preempted requests "
                "parked in host RAM awaiting resume",
                "# TYPE bigdl_tpu_preempted_waiting gauge",
                f"bigdl_tpu_preempted_waiting {len(self.engine._preempted)}",
                "# HELP bigdl_tpu_journal_corrupt_lines_total interior-"
                "corrupt journal lines skipped at recovery scan",
                "# TYPE bigdl_tpu_journal_corrupt_lines_total counter",
                f"bigdl_tpu_journal_corrupt_lines_total "
                f"{getattr(self.engine, 'journal_corrupt_lines', 0)}",
            ]
            lines += self.engine.queue_wait.render(
                "bigdl_tpu_queue_wait_seconds",
                "submit-to-first-admission wait (prefill excluded)",
            )
            # ---- request-lifecycle latency + utilization families
            # (docs/observability.md; ISSUE 11) ----
            lines += [
                "# HELP bigdl_tpu_uptime_seconds engine age (its own "
                "clock domain)",
                "# TYPE bigdl_tpu_uptime_seconds gauge",
                f"bigdl_tpu_uptime_seconds "
                f"{self.engine.uptime_seconds():.3f}",
                "# HELP bigdl_tpu_batch_occupancy fraction of decode "
                "slots in use",
                "# TYPE bigdl_tpu_batch_occupancy gauge",
                f"bigdl_tpu_batch_occupancy "
                f"{busy / max(self.engine.n_slots, 1):.4f}",
                "# HELP bigdl_tpu_kv_pool_utilization fraction of the "
                "KV pool holding live state",
                "# TYPE bigdl_tpu_kv_pool_utilization gauge",
                f"bigdl_tpu_kv_pool_utilization "
                f"{self.engine.kv_utilization():.4f}",
                "# HELP bigdl_tpu_requests_finished_total requests "
                "reaching a terminal state, by finish_reason",
                "# TYPE bigdl_tpu_requests_finished_total counter",
            ]
            # snapshot under the writers' lock (handler threads insert
            # first-seen reasons concurrently via _note_finish)
            with self.engine._stat_lock:
                fr = dict(self.engine.finish_reasons)
            for reason in FINISH_REASONS + tuple(
                sorted(set(fr) - set(FINISH_REASONS))
            ):
                lines.append(
                    f'bigdl_tpu_requests_finished_total'
                    f'{{reason="{reason}"}} {fr.get(reason, 0)}'
                )
            lines += self.engine.ttft.render(
                "bigdl_tpu_ttft_seconds",
                "time to first token (submit to first emit)",
            )
            lines += self.engine.itl.render(
                "bigdl_tpu_inter_token_seconds",
                "gap between consecutive emitted tokens (parked time "
                "excluded — see resume_wait)",
            )
            lines += self.engine.prefill_seconds.render(
                "bigdl_tpu_prefill_seconds",
                "prefill phase per admission (admission to first-token "
                "sample)",
            )
            lines += self.engine.decode_step_seconds.render(
                "bigdl_tpu_decode_step_seconds",
                "batched decode step wall-clock (host-sync honest)",
            )
            lines += self.engine.resume_wait.render(
                "bigdl_tpu_resume_wait_seconds",
                "preempted requests' host-RAM parked time until resume "
                "(not folded into queue_wait)",
            )
            lines += [
                # chunked prefill (docs/serving.md §6): one count per
                # prefill dispatch — a monolithic prefill is 1 chunk
                "# HELP bigdl_tpu_prefill_chunks_total prefill chunks "
                "dispatched (monolithic prefill counts 1)",
                "# TYPE bigdl_tpu_prefill_chunks_total counter",
                f"bigdl_tpu_prefill_chunks_total "
                f"{self.engine.prefill_chunks}",
            ]
            if self.engine.paged:
                lines += [
                    "# HELP bigdl_tpu_free_pages allocatable KV pages",
                    "# TYPE bigdl_tpu_free_pages gauge",
                    f"bigdl_tpu_free_pages {len(self.engine._free_pages)}",
                    "# HELP bigdl_tpu_prefix_hits_total full-page prefix "
                    "cache hits",
                    "# TYPE bigdl_tpu_prefix_hits_total counter",
                    f"bigdl_tpu_prefix_hits_total {self.engine.prefix_hits}",
                    "# HELP bigdl_tpu_prefix_partial_hits_total sub-page "
                    "prefix copies",
                    "# TYPE bigdl_tpu_prefix_partial_hits_total counter",
                    f"bigdl_tpu_prefix_partial_hits_total "
                    f"{self.engine.prefix_partial_hits}",
                    "# HELP bigdl_tpu_prefix_tokens_reused_total prompt "
                    "tokens served from copied KV instead of prefill",
                    "# TYPE bigdl_tpu_prefix_tokens_reused_total counter",
                    f"bigdl_tpu_prefix_tokens_reused_total "
                    f"{self.engine.prefix_tokens_reused}",
                    # radix prefix cache (serving/radix.py)
                    "# HELP bigdl_tpu_prefix_evictions_total radix "
                    "cache leaves evicted for page pressure",
                    "# TYPE bigdl_tpu_prefix_evictions_total counter",
                    f"bigdl_tpu_prefix_evictions_total "
                    f"{self.engine.prefix_evictions}",
                    "# HELP bigdl_tpu_radix_nodes cached prefix pages "
                    "(radix tree nodes)",
                    "# TYPE bigdl_tpu_radix_nodes gauge",
                    f"bigdl_tpu_radix_nodes {self.engine.radix.n_nodes}",
                ]
            if getattr(self.engine, "adapters", None) is not None:
                # multi-tenant LoRA registry (serving/adapters.py §7)
                st = self.engine.adapters.stats()
                lines += [
                    "# HELP bigdl_tpu_adapter_loads_total LoRA adapter "
                    "artifact loads (incl. post-eviction reloads)",
                    "# TYPE bigdl_tpu_adapter_loads_total counter",
                    f"bigdl_tpu_adapter_loads_total {st['loads']}",
                    "# HELP bigdl_tpu_adapter_evictions_total adapters "
                    "dropped from host RAM under budget pressure",
                    "# TYPE bigdl_tpu_adapter_evictions_total counter",
                    f"bigdl_tpu_adapter_evictions_total {st['evictions']}",
                    "# HELP bigdl_tpu_adapter_load_failures_total "
                    "missing/corrupt/rank-mismatched adapter loads",
                    "# TYPE bigdl_tpu_adapter_load_failures_total counter",
                    f"bigdl_tpu_adapter_load_failures_total "
                    f"{st['load_failures']}",
                    "# HELP bigdl_tpu_adapters_resident adapters "
                    "currently resident in host RAM",
                    "# TYPE bigdl_tpu_adapters_resident gauge",
                    f"bigdl_tpu_adapters_resident {st['resident']}",
                ]
                # unified HBM paging (docs/serving.md §7): device
                # residency in the shared KV page pool. Families render
                # whenever the adapter block does (0 when the engine has
                # no pager — dense pool or family cache) so the drift
                # gate stays structural, not configuration-dependent.
                pager = getattr(self.engine, "_pager", None)
                pi = pager.page_ins if pager is not None else 0
                po = pager.page_outs if pager is not None else 0
                pr = pager.pages_resident if pager is not None else 0
                lines += [
                    "# HELP bigdl_tpu_adapter_page_ins_total adapter "
                    "weight pages written into the shared KV page pool",
                    "# TYPE bigdl_tpu_adapter_page_ins_total counter",
                    f"bigdl_tpu_adapter_page_ins_total {pi}",
                    "# HELP bigdl_tpu_adapter_page_outs_total adapter "
                    "weight pages dropped back to host under pressure",
                    "# TYPE bigdl_tpu_adapter_page_outs_total counter",
                    f"bigdl_tpu_adapter_page_outs_total {po}",
                    "# HELP bigdl_tpu_adapter_pages_resident device "
                    "pages currently holding adapter weights",
                    "# TYPE bigdl_tpu_adapter_pages_resident gauge",
                    f"bigdl_tpu_adapter_pages_resident {pr}",
                ]
            if self.engine.speculative:
                lines += [
                    "# HELP bigdl_tpu_spec_rounds_total verify rounds run",
                    "# TYPE bigdl_tpu_spec_rounds_total counter",
                    f"bigdl_tpu_spec_rounds_total {self.engine.spec_rounds}",
                    "# HELP bigdl_tpu_spec_emitted_total tokens emitted by "
                    "verify rounds",
                    "# TYPE bigdl_tpu_spec_emitted_total counter",
                    f"bigdl_tpu_spec_emitted_total {self.engine.spec_emitted}",
                    "# HELP bigdl_tpu_spec_draft_k current draft length "
                    "(ladder-steered when adaptive_draft)",
                    "# TYPE bigdl_tpu_spec_draft_k gauge",
                    f"bigdl_tpu_spec_draft_k {self.engine._cur_k}",
                ]
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# exposition-drift registry: the authoritative list of metric families a
# render must contain. scripts/ci.sh --core fails when render() and this
# registry disagree in EITHER direction — a family can neither silently
# vanish from /metrics nor ship unregistered (docs/observability.md).
# ---------------------------------------------------------------------------

_PROCESS_FAMILIES = (
    "bigdl_tpu_requests_total",
    "bigdl_tpu_tokens_generated_total",
    "bigdl_tpu_requests_failed_total",
    "bigdl_tpu_checkpoint_verify_failures_total",
    "bigdl_tpu_build_info",
    "bigdl_tpu_train_anomalies_total",
    "bigdl_tpu_train_steps_skipped_total",
    "bigdl_tpu_train_rollbacks_total",
    "bigdl_tpu_train_emergency_checkpoints_total",
    "bigdl_tpu_train_watchdog_aborts_total",
    "bigdl_tpu_train_step_seconds",
    "bigdl_tpu_request_seconds",
)

_ENGINE_FAMILIES = (
    "bigdl_tpu_busy_slots",
    "bigdl_tpu_total_slots",
    "bigdl_tpu_queue_depth",
    "bigdl_tpu_preemptions_total",
    "bigdl_tpu_preemption_resumes_total",
    "bigdl_tpu_requests_shed_total",
    "bigdl_tpu_request_timeouts_total",
    "bigdl_tpu_preempted_waiting",
    "bigdl_tpu_journal_corrupt_lines_total",
    "bigdl_tpu_queue_wait_seconds",
    "bigdl_tpu_uptime_seconds",
    "bigdl_tpu_batch_occupancy",
    "bigdl_tpu_kv_pool_utilization",
    "bigdl_tpu_requests_finished_total",
    "bigdl_tpu_ttft_seconds",
    "bigdl_tpu_inter_token_seconds",
    "bigdl_tpu_prefill_seconds",
    "bigdl_tpu_decode_step_seconds",
    "bigdl_tpu_resume_wait_seconds",
    "bigdl_tpu_prefill_chunks_total",
)

_PAGED_FAMILIES = (
    "bigdl_tpu_free_pages",
    "bigdl_tpu_prefix_hits_total",
    "bigdl_tpu_prefix_partial_hits_total",
    "bigdl_tpu_prefix_tokens_reused_total",
    "bigdl_tpu_prefix_evictions_total",
    "bigdl_tpu_radix_nodes",
)

_SPEC_FAMILIES = (
    "bigdl_tpu_spec_rounds_total",
    "bigdl_tpu_spec_emitted_total",
    "bigdl_tpu_spec_draft_k",
)

_ADAPTER_FAMILIES = (
    "bigdl_tpu_adapter_loads_total",
    "bigdl_tpu_adapter_evictions_total",
    "bigdl_tpu_adapter_load_failures_total",
    "bigdl_tpu_adapters_resident",
    "bigdl_tpu_adapter_page_ins_total",
    "bigdl_tpu_adapter_page_outs_total",
    "bigdl_tpu_adapter_pages_resident",
)


def expected_families(engine=None) -> list:
    """Every metric family a `Metrics(engine).render()` must expose."""
    names = list(_PROCESS_FAMILIES)
    if engine is not None:
        names += _ENGINE_FAMILIES
        if getattr(engine, "paged", False):
            names += _PAGED_FAMILIES
        if getattr(engine, "adapters", None) is not None:
            names += _ADAPTER_FAMILIES
        if getattr(engine, "speculative", False):
            names += _SPEC_FAMILIES
    return names


def metric_drift(rendered: str, engine=None) -> tuple:
    """(missing, unregistered): families the registry expects but the
    exposition lacks, and families rendered but absent from the
    registry. Both empty = no drift."""
    import re

    got = set(re.findall(r"^# TYPE (\S+) \S+", rendered, flags=re.M))
    want = set(expected_families(engine))
    return sorted(want - got), sorted(got - want)
